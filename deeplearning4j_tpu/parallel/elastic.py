"""Elastic multi-host training: bounded-staleness local-SGD sync rounds
with host dropout and rejoin.

The reference's multi-node story is a Spark driver plus an empty
parameter-server stub; ROADMAP item 5 calls for the TPU-first framework
to own the real thing: async local-SGD over DCN that survives
preemption. The design target is classic related work — bounded
staleness in the SSP style (Ho et al., NeurIPS 2013) composed with
communication-efficient local SGD (Lin et al., ICLR 2020) — built on the
substrate this repo already has: local-SGD semantics
(:mod:`.wrapper`), digest agreement (:func:`.distributed.agree_on_digest`),
durable exact-resume (:mod:`..util.durable`), deadline/clock injection
(:mod:`..util.resilience`) and the flight recorder
(:mod:`..util.flightrecorder`).

Protocol ("delayed-correction local SGD", staleness window ``s``):

- The fleet is a STATIC spec of host ids; each host runs its own process
  (no ``jax.distributed`` collectives — a collective would hang on a
  dead peer, the exact failure mode this layer exists to survive).
  Coordination happens through a shared :class:`CoordinationStore` (a
  durable bulletin board: the filesystem all hosts mount, or an
  in-memory store for single-process tests).
- Round ``r`` on host ``h``: run ``k`` local steps from params
  ``p_h(r)``, publish the local delta ``d_h(r)`` (atomic, content-
  digested, idempotent), then apply the DELAYED correction for round
  ``j = r - s``::

      p_h(r+1) = p_h(r) + d_h(r) + ( R(j) - d_h(j) )      # j = r - s
      R(j)     = mean over members(j) of d_·(j)

  Telescoping gives ``p_h(r) = p0 + Σ_{j<=r-1-s} R(j) +
  Σ_{r-s<=i<r} d_h(i)`` — host states differ only in their last ``s``
  local deltas, and the whole chain is a deterministic function of the
  data schedule and the membership log, independent of wall-clock
  interleaving. That determinism is what makes kill/rejoin chaos
  provable bit-exactly.
- **Bounded staleness**: finishing round ``r`` needs ``R(r-s)``, so a
  host blocks only when it would run more than ``s`` rounds ahead of the
  slowest live member. While blocked it keeps heartbeating and the
  flight recorder names exactly which host is stalling the round.
- **Membership**: heartbeats are published from the MAIN loop (round
  boundaries and wait polls) — a hung main thread therefore stops
  heartbeating and its lease expires; a background heartbeat thread
  would mask exactly the hang we must detect. Lease expiry flips the
  observer's view to ``dead`` (``membership_transitions_total
  {event="evict"}``); a fresh heartbeat from a restarted incarnation
  flips it back (``event="rejoin"``). The VIEW drives metrics and
  attribution only — round MATH changes only through the append-only
  membership LOG: when a reduction has been blocked past
  ``evict_after_s`` on a lease-expired host, the blocked survivor writes
  a create-once eviction record (effective round = the victim's last
  published round + 1) and the round reduces over the survivors. A
  create-once ``REDUCE`` record pins the membership every host must use
  for that round, so racing observers cannot disagree.
- **Rejoin**: a restarted host restores the newest durable snapshot from
  its own :class:`~deeplearning4j_tpu.util.durable.CheckpointStore`
  (params + updater + counters + round cursor) and FAST-FORWARDS by
  replaying its missed rounds — recomputed deltas must match the
  digests of anything it already published (replay divergence refuses
  loudly), and the backfilled contributions release any survivor
  blocked at the staleness bound. A host that was hard-evicted cannot
  backfill (its missed rounds already reduced without it); it rejoins
  as a NEW member instead: re-seed from ``p0``, apply the published
  reduction history, and write a rejoin record effective beyond the
  fleet's reduce frontier. Either way the final barrier digest is
  checked with :func:`..parallel.distributed.agree_on_digest` over a
  store-backed allgather.

Scope notes: the correction protocol covers PARAMS; updater state and
layer state (BN statistics) stay host-local between snapshots, exactly
like the in-process local-SGD mode between averaging points. Round
artifacts are retained for the run's lifetime (they are how an evicted
host reconstructs the chain); production deployments would GC rounds
older than the newest fleet snapshot.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import io
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..util import faults as _faults
from ..util import flightrecorder as _flight
from ..util import metrics as _metrics
from ..util import tracing as _tracing
from ..util.resilience import SYSTEM_CLOCK, Clock, Deadline
from .distributed import agree_on_digest

logger = logging.getLogger("deeplearning4j_tpu")


class ElasticProtocolError(RuntimeError):
    """The round protocol reached an inconsistent state (diverged replay,
    digest disagreement, conflicting reduce membership)."""


# ----------------------------------------------------------------------
# coordination store: the durable bulletin board
# ----------------------------------------------------------------------

class CoordinationStore:
    """Tiny KV bulletin board with atomic create-once publish.

    The elastic protocol needs exactly three properties: (1) ``put`` is
    atomic (a reader never sees a torn value), (2) create-once ``put``
    (``overwrite=False``) is an atomic test-and-set — the winner of a
    race is decided by the store, and (3) keys are listable by prefix.
    The file implementation maps keys to files under one directory
    (tmp-write + ``link``/``replace``); the in-memory implementation
    backs single-process protocol tests.
    """

    def put(self, key: str, data: bytes, *, overwrite: bool = False) -> bool:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    # -- JSON convenience ----------------------------------------------

    def put_json(self, key: str, doc: dict, *, overwrite: bool = False) -> bool:
        return self.put(key, json.dumps(doc, sort_keys=True).encode(),
                        overwrite=overwrite)

    def get_json(self, key: str) -> Optional[dict]:
        raw = self.get(key)
        return None if raw is None else json.loads(raw.decode())


class FileCoordinationStore(CoordinationStore):
    """Keys are relative paths under ``directory``; values are files.

    Atomicity: values land in a per-process tmp name first, then
    ``os.link`` (create-once: EEXIST loses the race) or ``os.replace``
    (overwrite) into place — readers see old-or-new bytes, never torn.
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._seq = 0
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        base = os.path.normpath(self.directory)
        path = os.path.normpath(os.path.join(base, key))
        # bare startswith would accept SIBLINGS sharing the store path
        # as a prefix (/data/fleet matching /data/fleet2/...)
        if path != base and not path.startswith(base + os.sep):
            raise ValueError(f"key escapes the store: {key!r}")
        return path

    def put(self, key: str, data: bytes, *, overwrite: bool = False) -> bool:
        final = self._path(key)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        with self._lock:
            self._seq += 1
            n = self._seq
        tmp = os.path.join(os.path.dirname(final),
                           f".tmp_{os.getpid()}_{n}_{os.path.basename(final)}")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            if overwrite:
                os.replace(tmp, final)
                return True
            try:
                os.link(tmp, final)
                return True
            except FileExistsError:
                return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (FileNotFoundError, IsADirectoryError, NotADirectoryError):
            return None

    def list(self, prefix: str) -> List[str]:
        base = self._path(prefix) if prefix else self.directory
        if not os.path.isdir(base):
            return []
        out = []
        for name in os.listdir(base):
            if name.startswith(".tmp_"):
                continue
            full = os.path.join(base, name)
            rel = os.path.join(prefix, name) if prefix else name
            if os.path.isfile(full):
                out.append(rel)
        return sorted(out)


class InMemoryCoordinationStore(CoordinationStore):
    """Thread-safe dict store for single-process protocol tests."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key: str, data: bytes, *, overwrite: bool = False) -> bool:
        with self._lock:
            if not overwrite and key in self._data:
                return False
            self._data[key] = bytes(data)
            return True

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def list(self, prefix: str) -> List[str]:
        norm = prefix.rstrip("/") + "/" if prefix else ""
        with self._lock:
            keys = list(self._data)
        out = []
        for k in keys:
            if not k.startswith(norm):
                continue
            rest = k[len(norm):]
            if "/" not in rest:         # direct children only, like listdir
                out.append(k)
        return sorted(out)


# ----------------------------------------------------------------------
# leaf packing: deterministic bytes for contributions/reductions
# ----------------------------------------------------------------------

def pack_leaves(leaves: Sequence[np.ndarray]) -> bytes:
    """Deterministic framing (unlike npz, whose zip metadata can vary):
    one JSON header line with dtypes/shapes, then the raw leaf bytes."""
    arrs = [np.asarray(a) for a in leaves]
    header = json.dumps([{"dtype": str(a.dtype), "shape": list(a.shape)}
                         for a in arrs]).encode()
    buf = io.BytesIO()
    buf.write(header + b"\n")
    for a in arrs:
        buf.write(np.ascontiguousarray(a).tobytes())
    return buf.getvalue()


def unpack_leaves(data: bytes) -> List[np.ndarray]:
    nl = data.index(b"\n")
    metas = json.loads(data[:nl].decode())
    out, off = [], nl + 1
    for m in metas:
        dt = np.dtype(m["dtype"])
        n = int(np.prod(m["shape"])) if m["shape"] else 1
        nbytes = n * dt.itemsize
        a = np.frombuffer(data[off:off + nbytes], dtype=dt)
        out.append(a.reshape(m["shape"]).copy())
        off += nbytes
    return out


def leaves_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ----------------------------------------------------------------------
# metric families
# ----------------------------------------------------------------------

_ROUND_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                  10.0, 30.0, 60.0)


def _reg(registry=None) -> _metrics.MetricsRegistry:
    return registry if registry is not None else _metrics.REGISTRY


def rounds_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "sync_rounds_total",
        "Elastic local-SGD sync rounds completed (local steps + publish + "
        "delayed correction)", ("host",))


def round_seconds_histogram(registry=None) -> _metrics.Histogram:
    return _reg(registry).histogram(
        "sync_round_seconds",
        "Wall time of one elastic sync round, including any blocked wait "
        "at the staleness bound", ("host",), buckets=_ROUND_BUCKETS)


def round_wait_seconds_histogram(registry=None) -> _metrics.Histogram:
    return _reg(registry).histogram(
        "sync_round_wait_seconds",
        "Portion of the round spent blocked waiting for a delayed "
        "correction (0 in steady state — the staleness window hides "
        "peer jitter)", ("host",), buckets=_ROUND_BUCKETS)


def staleness_gauge(registry=None) -> _metrics.Gauge:
    return _reg(registry).gauge(
        "staleness_window",
        "How many rounds this host is ahead of the slowest live member "
        "(bounded by max_staleness)", ("host",))


def transitions_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "membership_transitions_total",
        "Fleet membership transitions as observed by this process "
        "(join/evict/rejoin at the heartbeat-lease level, hard_evict "
        "when a round is reduced without the host)", ("event", "host"))


# ----------------------------------------------------------------------
# configuration
# ----------------------------------------------------------------------

@dataclasses.dataclass
class ElasticConfig:
    """Fleet spec + protocol knobs for one elastic host.

    ``fleet`` is the ordered host-id spec (identical on every host);
    ``host`` is this process's id and must be in ``fleet``.
    ``max_staleness`` is the SSP window ``s``: a host blocks only when it
    would run more than ``s`` rounds ahead of the slowest live member
    (``s=0`` is fully synchronous local SGD). ``lease_s`` bounds how
    stale a peer's heartbeat may be before the view marks it dead;
    ``evict_after_s`` (default ``lease_s``) is how long a REDUCTION may
    stay blocked on a dead peer before a survivor hard-evicts it from
    the round. ``clock`` is injectable and governs this host's WAITS
    (poll sleeps, eviction deadlines); heartbeat timestamps and lease
    math are deliberately wall-clock ``time.time()`` — they are compared
    ACROSS processes, where an injected per-process clock has no
    meaning. Deterministic tests therefore script failures by killing
    hosts (leases then expire in real time), not by warping the clock.
    """

    fleet: Tuple[str, ...]
    host: str
    steps_per_round: int = 4
    max_staleness: int = 1
    lease_s: float = 10.0
    evict_after_s: Optional[float] = None
    poll_s: float = 0.02
    heartbeat_every_s: Optional[float] = None
    checkpoint_every_rounds: int = 1
    clock: Clock = SYSTEM_CLOCK

    def __post_init__(self):
        self.fleet = tuple(self.fleet)
        if self.host not in self.fleet:
            raise ValueError(f"host {self.host!r} not in fleet {self.fleet}")
        if len(set(self.fleet)) != len(self.fleet):
            raise ValueError(f"duplicate host ids in fleet {self.fleet}")
        if self.steps_per_round < 1:
            raise ValueError("steps_per_round must be >= 1")
        if self.max_staleness < 0:
            raise ValueError("max_staleness must be >= 0")
        if self.evict_after_s is None:
            self.evict_after_s = float(self.lease_s)
        if self.heartbeat_every_s is None:
            self.heartbeat_every_s = max(self.poll_s, self.lease_s / 4.0)


# ----------------------------------------------------------------------
# lease membership: the reusable liveness layer
# ----------------------------------------------------------------------

class LeaseMembership:
    """Heartbeat-lease membership view over a :class:`CoordinationStore`.

    The liveness core of the elastic protocol, factored out so training
    fleets and serving fleets share one lease discipline. Members publish
    an overwritten ``<prefix>/<member>.json`` doc stamped with wall-clock
    ``ts``; any observer derives live/dead from lease age and records
    join/evict/rejoin transitions into ``membership_transitions_total``
    plus the flight recorder. Two membership styles:

    - **static** (training): ``members`` is the fleet spec; a spec host
      that never heartbeats becomes an evict once the join grace expires.
    - **dynamic** (serving): ``members=None``; the member set is
      discovered from the store listing, so replicas self-register by
      publishing their first heartbeat and observers need no fleet spec.

    Timestamps are deliberately ``time.time()`` — they are compared
    ACROSS processes, where an injected per-process clock has no meaning
    (see :class:`ElasticConfig`). Tests script failures by killing
    members, not by warping the clock.
    """

    def __init__(self, store: CoordinationStore, *, observer: str,
                 lease_s: float, members: Optional[Sequence[str]] = None,
                 prefix: str = "hb", join_grace_s: Optional[float] = None,
                 registry=None, flight_kind: str = "elastic_membership"):
        if lease_s <= 0:
            raise ValueError(f"lease_s must be > 0, got {lease_s}")
        self.store = store
        self.observer = observer
        self.lease_s = float(lease_s)
        self.prefix = prefix.strip("/")
        self.static_members = (None if members is None
                               else tuple(members))
        self.registry = registry
        self.flight_kind = flight_kind
        # lease-level view for metrics/attribution: member -> status
        self._view: Dict[str, str] = {
            h: "unseen" for h in (self.static_members or ())}
        # join grace: a member that has NEVER heartbeat is not lease-dead
        # while processes are still starting up (first compiles run long
        # before the first publish); it becomes evictable once the grace
        # from OUR start expires
        self._born = time.time()
        self.join_grace_s = (3.0 * self.lease_s if join_grace_s is None
                             else float(join_grace_s))

    # -- publish side --------------------------------------------------

    def _key(self, member: str) -> str:
        return f"{self.prefix}/{member}.json"

    def next_incarnation(self, member: str) -> int:
        doc = self.store.get_json(self._key(member))
        return (int(doc.get("incarnation", 0)) + 1) if doc else 1

    def publish(self, member: str, doc: dict) -> None:
        """Write ``member``'s heartbeat doc, stamping wall-clock ``ts``.

        Callers own the heartbeat CADENCE (and, crucially, which thread
        publishes: liveness must be attested from the loop whose hang
        should expire the lease)."""
        body = dict(doc)
        body.setdefault("host", member)
        body["ts"] = time.time()
        self.store.put_json(self._key(member), body, overwrite=True)

    # -- observe side --------------------------------------------------

    def members(self) -> Tuple[str, ...]:
        """Static spec if given, else every member ever seen in the
        store. The discovered set only grows — a vanished member
        transitions to dead via its stale lease, not by key removal."""
        if self.static_members is not None:
            return self.static_members
        seen = set(self._view)
        plen = len(self.prefix) + 1
        for key in self.store.list(self.prefix):
            name = key[plen:]
            if name.endswith(".json"):
                seen.add(name[: -len(".json")])
        return tuple(sorted(seen))

    def view(self) -> Dict[str, dict]:
        """Refresh the lease-level view; records join/evict/rejoin
        transitions into metrics + the flight recorder. Each member's
        entry carries ``alive``/``done``/``round``/``incarnation``/
        ``age_s`` plus the raw heartbeat ``doc`` (serving members
        advertise capacity and readiness there)."""
        now = time.time()
        out: Dict[str, dict] = {}
        for h in self.members():
            doc = self.store.get_json(self._key(h)) or {}
            ts = float(doc.get("ts", -1e18))
            done = doc.get("status") == "done"
            alive = done or (now - ts) <= self.lease_s
            in_grace = (now - self._born) <= self.join_grace_s
            if not doc and in_grace:
                alive = True        # starting up (first compile)
            out[h] = {"alive": alive, "done": done,
                      "round": int(doc.get("round", -1)),
                      "incarnation": int(doc.get("incarnation", 0)),
                      "age_s": None if not doc else now - ts,
                      "doc": doc}
            prev = self._view.get(h, "unseen")
            # a never-heartbeat host stays "unseen" through the grace
            # (no spurious join), then turns dead — so a host that never
            # came up reports as an evict, not as a silent unseen
            new = ("done" if done
                   else "live" if doc and alive
                   else "dead" if doc or not in_grace
                   else "unseen")
            if new != prev:
                self._view[h] = new
                event = None
                if prev == "unseen" and new in ("live", "done"):
                    event = "join"
                elif prev in ("live", "done", "unseen") and new == "dead":
                    event = "evict"
                elif prev == "dead" and new in ("live", "done"):
                    event = "rejoin"
                if event is not None:
                    transitions_counter(self.registry).inc(
                        event=event, host=h)
                    _flight.record(self.flight_kind, event=event,
                                   host=h, observer=self.observer,
                                   incarnation=out[h]["incarnation"],
                                   peer_round=out[h]["round"])
        return out


# ----------------------------------------------------------------------
# coordinator: heartbeats, membership log, round ledger
# ----------------------------------------------------------------------

_EV_EVICT = "evict"
_EV_REJOIN = "rejoin"


class ElasticCoordinator:
    """One host's handle on the shared bulletin board.

    Key layout::

        hb/<host>.json                     heartbeat (overwritten)
        log/<seq>_<event>_<host>.json      membership log (create-once)
        rounds/r<round>/<host>.bin         contribution (create-once)
        rounds/r<round>/REDUCE.json        reduce record (create-once)
        final/<host>.json                  final digest barrier
    """

    def __init__(self, store: CoordinationStore, cfg: ElasticConfig, *,
                 registry=None):
        self.store = store
        self.cfg = cfg
        self.registry = registry
        self.host = cfg.host
        self.membership = LeaseMembership(
            store, observer=cfg.host, lease_s=cfg.lease_s,
            members=cfg.fleet, registry=registry)
        self.incarnation = self.membership.next_incarnation(cfg.host)
        self._last_hb = -1e18
        self._log_cache: Optional[Tuple[Tuple[str, ...], List[dict]]] = None

    # -- heartbeats ----------------------------------------------------

    def heartbeat(self, round_: int, status: str = "live", *,
                  force: bool = False) -> None:
        """Publish liveness from the MAIN loop only — a hung main thread
        must stop heartbeating so its lease can expire."""
        now = time.time()
        if not force and now - self._last_hb < self.cfg.heartbeat_every_s:
            return
        self._last_hb = now
        self.membership.publish(self.host, {
            "host": self.host, "incarnation": self.incarnation,
            "round": int(round_), "status": status})

    def fleet_view(self) -> Dict[str, dict]:
        """Refresh the lease-level view; records join/evict/rejoin
        transitions into metrics + the flight recorder."""
        return self.membership.view()

    # -- membership log (round math) -----------------------------------

    def membership_log(self) -> List[dict]:
        keys = tuple(self.store.list("log"))
        cached = self._log_cache
        if cached is not None and cached[0] == keys:
            return cached[1]
        recs = []
        for key in keys:
            doc = self.store.get_json(key)
            if doc is not None:
                recs.append(doc)
        recs.sort(key=lambda d: int(d["seq"]))
        # append-only log: safe to cache per key listing (one remote
        # LIST per poll instead of O(records) remote reads)
        self._log_cache = (keys, recs)
        return recs

    def _append_log(self, event: str, host: str, effective_round: int,
                    **extra) -> dict:
        recs = self.membership_log()
        seq = (int(recs[-1]["seq"]) + 1) if recs else 1
        # membership changes are written from inside a round/fit span:
        # stamping the active trace id lets the timeline collector tie
        # an eviction to the exact round trace that observed it
        span = _tracing.active_span()
        if span is not None:
            extra.setdefault("trace_id", span.trace_id)
        while True:
            doc = {"seq": seq, "event": event, "host": host,
                   "effective_round": int(effective_round),
                   "by": self.host, "ts": time.time(), **extra}
            # key is the SEQ alone: two concurrent appends must collide
            # on the create-once put (a key that also carried event/host
            # would let both land with the same seq, leaving tie order
            # to filename alphabetics instead of causality)
            if self.store.put_json(f"log/{seq:06d}.json", doc):
                self._log_cache = None
                return doc
            seq += 1            # lost the seq race; append after the winner

    def member_at(self, host: str, round_: int) -> bool:
        decided = True          # fleet-spec hosts start as members
        for rec in self.membership_log():
            if rec["host"] != host or rec["effective_round"] > round_:
                continue
            decided = rec["event"] == _EV_REJOIN
        return decided

    def members_for_round(self, round_: int) -> Tuple[str, ...]:
        return tuple(h for h in self.cfg.fleet if self.member_at(h, round_))

    def eviction_of(self, host: str) -> Optional[dict]:
        """The newest membership record for ``host`` if it is an
        eviction (i.e. the host is currently hard-evicted), else None."""
        last = None
        for rec in self.membership_log():
            if rec["host"] == host:
                last = rec
        return last if last is not None and last["event"] == _EV_EVICT \
            else None

    def hard_evict(self, host: str, *, blocked_round: int) -> dict:
        """Remove ``host`` from every round it has not published
        (effective = last published round + 1 — rounds it DID publish
        stay intact, so no already-consumed reduction is invalidated)."""
        effective = self.last_published_round(host, upto=blocked_round) + 1
        self._log_cache = None
        existing = self.eviction_of(host)
        if existing is not None and \
                int(existing["effective_round"]) <= effective:
            # a racing survivor already evicted this host for these
            # rounds — don't duplicate the record or the metric
            return existing
        rec = self._append_log(_EV_EVICT, host, effective,
                               blocked_round=int(blocked_round))
        transitions_counter(self.registry).inc(event="hard_evict",
                                               host=host)
        _flight.record("elastic_evict", host=host, by=self.host,
                       effective_round=effective,
                       blocked_round=int(blocked_round))
        logger.warning(
            "elastic: hard-evicted %s from round %d on (blocked on round "
            "%d past the eviction deadline)", host, effective,
            blocked_round)
        return rec

    def rejoin(self, host: str, effective_round: int,
               incarnation: int) -> dict:
        rec = self._append_log(_EV_REJOIN, host, effective_round,
                               incarnation=int(incarnation))
        _flight.record("elastic_rejoin", host=host,
                       effective_round=int(effective_round),
                       incarnation=int(incarnation))
        return rec

    # -- round ledger --------------------------------------------------

    @staticmethod
    def _round_dir(round_: int) -> str:
        return f"rounds/r{round_:06d}"

    def publish_contribution(self, round_: int,
                             leaves: Sequence[np.ndarray]) -> str:
        """Atomic, idempotent publish of this host's round delta. A
        replayed publish must be BIT-IDENTICAL to what an earlier
        incarnation published — a digest mismatch means nondeterministic
        replay, which would silently corrupt the chain, so it refuses."""
        payload = pack_leaves(leaves)
        digest = leaves_digest(payload)
        key = f"{self._round_dir(round_)}/{self.host}.bin"
        if not self.store.put(key, payload):
            existing = self.store.get(key)
            if existing is None or leaves_digest(existing) != digest:
                raise ElasticProtocolError(
                    f"replayed contribution for round {round_} differs "
                    f"from the published one (host {self.host}) — "
                    "nondeterministic replay, refusing to continue")
        _flight.record("elastic_publish", host=self.host,
                       round=int(round_), digest=digest[:12])
        return digest

    def contribution(self, round_: int, host: str) \
            -> Optional[List[np.ndarray]]:
        raw = self.store.get(f"{self._round_dir(round_)}/{host}.bin")
        return None if raw is None else unpack_leaves(raw)

    def published_hosts(self, round_: int) -> Tuple[str, ...]:
        out = []
        for key in self.store.list(self._round_dir(round_)):
            name = os.path.basename(key)
            if name.endswith(".bin"):
                out.append(name[:-4])
        return tuple(sorted(out))

    def last_published_round(self, host: str, *, upto: int) -> int:
        for r in range(int(upto), -1, -1):
            if self.store.get(f"{self._round_dir(r)}/{host}.bin") is not None:
                return r
        return -1

    def reduce_record(self, round_: int) -> Optional[dict]:
        return self.store.get_json(f"{self._round_dir(round_)}/REDUCE.json")

    # -- round trace records (attribution, not protocol) ---------------

    def publish_trace(self, round_: int, spans: Sequence[dict]) -> None:
        """Export this host's round-``round_`` spans next to the REDUCE
        record (``trace_<host>.json``). Overwrite-mode and best-effort:
        a replayed round records the replay's timings, and a failing
        export must never fail the round it describes."""
        try:
            self.store.put_json(
                f"{self._round_dir(round_)}/trace_{self.host}.json",
                {"host": self.host, "round": int(round_),
                 "incarnation": self.incarnation, "spans": list(spans)},
                overwrite=True)
        except Exception:
            logger.exception("elastic: trace export for round %d failed",
                             round_)

    def trace_records(self, round_: int) -> List[dict]:
        out = []
        for key in self.store.list(self._round_dir(round_)):
            name = os.path.basename(key)
            if name.startswith("trace_") and name.endswith(".json"):
                doc = self.store.get_json(key)
                if doc is not None:
                    out.append(doc)
        return out

    def _compute_reduction(self, round_: int,
                           members: Sequence[str]) -> List[np.ndarray]:
        """Mean of the members' deltas in fleet order, accumulated in
        float64 — the op order is fixed, so every host computes the same
        bits."""
        acc: Optional[List[np.ndarray]] = None
        for h in members:
            leaves = self.contribution(round_, h)
            if leaves is None:
                raise ElasticProtocolError(
                    f"round {round_}: member {h} has no contribution")
            if acc is None:
                acc = [l.astype(np.float64) for l in leaves]
            else:
                acc = [a + l for a, l in zip(acc, leaves)]
        if acc is None:
            raise ElasticProtocolError(
                f"round {round_}: empty membership")
        return [a / float(len(members)) for a in acc]

    def try_reduce(self, round_: int) -> Optional[List[np.ndarray]]:
        """Return round ``round_``'s reduction if computable now.

        An existing REDUCE record is AUTHORITATIVE (its member list pins
        the round even if the membership log has since changed);
        otherwise, once every current member's contribution is present,
        compute the mean and race to publish the record — the loser
        adopts the winner's membership.
        """
        rec = self.reduce_record(round_)
        if rec is None:
            members = self.members_for_round(round_)
            published = set(self.published_hosts(round_))
            if not members or not set(members) <= published:
                return None
            red = self._compute_reduction(round_, members)
            digest = leaves_digest(pack_leaves(red))
            if not self.store.put_json(
                    f"{self._round_dir(round_)}/REDUCE.json",
                    {"round": int(round_), "members": list(members),
                     "digest": digest, "by": self.host}):
                rec = self.reduce_record(round_)   # lost the race
            else:
                _flight.record("elastic_reduce", round=int(round_),
                               members=list(members), by=self.host)
                return red
        members = tuple(rec["members"])
        red = self._compute_reduction(round_, members)
        digest = leaves_digest(pack_leaves(red))
        if digest != rec["digest"]:
            raise ElasticProtocolError(
                f"round {round_}: recomputed reduction digest {digest[:12]} "
                f"!= published {rec['digest'][:12]} — hosts disagree on "
                "the round inputs")
        return red

    # -- final digest barrier ------------------------------------------

    def publish_final(self, digest: str) -> None:
        self.store.put_json(f"final/{self.host}.json",
                            {"host": self.host, "digest": digest,
                             "incarnation": self.incarnation},
                            overwrite=True)

    def final_digest_of(self, host: str) -> Optional[str]:
        doc = self.store.get_json(f"final/{host}.json")
        return None if doc is None else doc.get("digest")


# ----------------------------------------------------------------------
# the trainer
# ----------------------------------------------------------------------

def _net_param_leaves(net) -> List[np.ndarray]:
    import jax
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(net.params)]


def _set_net_params_from_leaves(net, leaves: Sequence[np.ndarray]) -> None:
    import jax
    flat, treedef = jax.tree_util.tree_flatten(net.params)
    assert len(flat) == len(leaves)
    net.params = jax.tree_util.tree_unflatten(treedef, list(leaves))


class ElasticTrainer:
    """Bounded-staleness elastic local-SGD over a shared coordination
    store. See the module docstring for the protocol.

    ``stepper_factory(net)`` (optional) builds the object whose
    ``fit_batch(x, y[, mask])`` runs one local step updating ``net`` in
    place — e.g. a sync-mode
    :class:`~deeplearning4j_tpu.parallel.wrapper.ParallelWrapper` so the
    local steps are themselves data-parallel over this host's devices.
    A factory (not an instance) because restore/rejoin can swap the
    underlying network, and the stepper's jitted closures must be
    rebuilt against the live one.

    ``fit(batch_fn, rounds=R)`` trains R rounds; ``batch_fn(round,
    step)`` returns ``(x, y)`` or ``(x, y, mask)`` and must be a pure
    function of its arguments (per-host seeded), which is what makes
    replay-on-rejoin exact. With ``checkpoint_dir`` set, construction
    restores the newest durable snapshot (round cursor included) and
    ``fit`` fast-forwards: it republishes the missed rounds
    (digest-verified) and rejoins the fleet without stopping anyone.
    """

    def __init__(self, net, store, cfg: ElasticConfig, *,
                 checkpoint_dir: Optional[str] = None,
                 registry=None, watchdog_s: Optional[float] = None,
                 handle_signals: bool = False, keep: int = 3,
                 stepper_factory: Optional[Callable] = None,
                 tracer: Optional[_tracing.Tracer] = None):
        from ..util.durable import CheckpointStore
        if isinstance(store, str):
            store = FileCoordinationStore(store)
        self.cfg = cfg
        self.registry = registry
        # per-trainer tracer named by the LOGICAL host id (not the
        # machine hostname): merged fleet timelines attribute phases to
        # fleet members. Root parent comes from DL4JTPU_TRACEPARENT when
        # the cluster scheduler (or the chaos harness) set one, so every
        # host's spans share the fleet trace id.
        self.tracer = tracer if tracer is not None \
            else _tracing.Tracer(host=cfg.host, registry=registry)
        self._round_spans: List[_tracing.Span] = []
        self.coord = ElasticCoordinator(store, cfg, registry=registry)
        self.watchdog_s = watchdog_s
        self.handle_signals = handle_signals
        self.preempted = False
        self.resumed = False
        self.agreed: Optional[bool] = None
        self.final_digest: Optional[str] = None
        self._fresh_net = net
        self._round = 0
        self._applied_next = 0      # next reduction round to fold in
        self._own_deltas: Dict[int, List[np.ndarray]] = {}
        # first round whose local delta belongs to THIS param chain —
        # corrections for earlier rounds fold in the full reduction
        # (a rejoined-as-new member's old-incarnation deltas are part of
        # R(j) like any other member's, never subtracted)
        self._member_from = 0
        self._held = None           # round-start TrainingState
        self._ctx: Dict[str, Any] = {"host": cfg.host}
        self._p0: Optional[List[np.ndarray]] = None
        self.ckpt_store = (CheckpointStore(checkpoint_dir, keep=keep)
                           if checkpoint_dir else None)
        if net is not None and net.params is None:
            net.init()
        self.net = net
        # p0 — the chain origin every host must share bit-for-bit (same
        # seed/init on every host). The final fleet state is
        # RECONSTRUCTED as p0 + sum of round reductions in one canonical
        # op order, because the incremental per-host chains reach the
        # same value only up to float non-associativity.
        self._p0 = _net_param_leaves(net) if net is not None else None
        if self.ckpt_store is not None:
            loaded = self.ckpt_store.load_latest()
            if loaded is not None:
                el = (loaded.cursor or {}).get("elastic", {})
                self.net = loaded.net
                self._round = int(el.get("round", 0))
                self._applied_next = max(0, self._round - cfg.max_staleness)
                self.resumed = True
                logger.info(
                    "elastic: host %s restored durable snapshot at round "
                    "%d (iter %d) — fast-forwarding", cfg.host,
                    self._round, loaded.iteration_count)
        self._stepper_factory = stepper_factory
        self.stepper = (stepper_factory(self.net) if stepper_factory
                        else self.net)
        self._watchdog = None
        self._preemption = None

    # -- helpers -------------------------------------------------------

    def _capture(self, kind: str = "round"):
        from ..util.durable import TrainingState
        cursor = {"elastic": {"round": self._round,
                              "host": self.cfg.host,
                              "incarnation": self.coord.incarnation}}
        return TrainingState.capture(self.net, cursor=cursor, kind=kind)

    def _write_snapshot(self, state) -> None:
        if self.ckpt_store is not None and state is not None:
            self.ckpt_store.save(state, registry=self.registry)

    def _stop_requested(self) -> bool:
        return (self._preemption is not None
                and self._preemption.requested)

    def _pet(self) -> None:
        if self._watchdog is not None:
            self._watchdog.pet()

    @contextlib.contextmanager
    def _span(self, name: str, **attrs):
        """A tracer span collected into the current round's export set."""
        with self.tracer.span(name, attributes=attrs) as s:
            self._round_spans.append(s)
            yield s

    def _record_span(self, name: str, seconds: float, **attrs) -> None:
        self._round_spans.append(
            self.tracer.record(name, seconds, attributes=attrs))

    # -- rejoin planning -----------------------------------------------

    def _plan_membership(self, rounds: int) -> None:
        """Decide how this incarnation participates: normal start,
        backfill fast-forward, or rejoin-as-new after a hard eviction."""
        ev = self.coord.eviction_of(self.cfg.host)
        if ev is None:
            if self.resumed:
                _flight.record("elastic_backfill", host=self.cfg.host,
                               from_round=self._round)
            return
        # hard-evicted: rounds >= effective were (or will be) reduced
        # without us — backfill is impossible. Rejoin as a NEW member:
        # re-seed from p0, fold in the published reduction history, and
        # pick an effective round beyond the fleet's reduce frontier.
        if self._fresh_net.params is None:
            self._fresh_net.init()
        self.net = self._fresh_net
        self.stepper = (self._stepper_factory(self.net)
                        if self._stepper_factory else self.net)
        s = self.cfg.max_staleness
        rho = -1
        while True:
            view = self.coord.fleet_view()
            frontier = max([v["round"] for v in view.values()]
                           + [int(ev["effective_round"])])
            rho = min(max(rho + 1, frontier + s + 2), rounds)
            if rho < rounds and self.coord.reduce_record(rho) is not None:
                continue        # already reduced without us: bump first
            self.coord.rejoin(self.cfg.host, rho, self.coord.incarnation)
            # a reduce that raced past our record pins us OUT of rho;
            # NEUTRALIZE the now-stale rejoin record (otherwise rounds
            # in [rho, rho') would count us as a member who never
            # publishes, stalling survivors into a second eviction) and
            # bump (effective rounds stay monotonic)
            rec = self.coord.reduce_record(rho)
            if rho >= rounds or rec is None \
                    or self.cfg.host in rec.get("members", ()):
                break
            self.coord._append_log(_EV_EVICT, self.cfg.host, rho,
                                   reason="rejoin_raced")
        transitions_counter(self.registry).inc(event="rejoin",
                                               host=self.cfg.host)
        self._round = rho
        self._applied_next = 0      # fold the full reduction history in
        self._own_deltas.clear()
        self._member_from = rho
        self.resumed = True
        logger.info(
            "elastic: host %s hard-evicted at round %d — rejoining as a "
            "new member from round %d", self.cfg.host,
            int(ev["effective_round"]), rho)

    # -- waits ---------------------------------------------------------

    def _await_reduce(self, round_: int) -> Optional[List[np.ndarray]]:
        """Block until round ``round_`` reduces. While blocked: keep
        heartbeating, attribute the stall to the missing hosts (flight
        recorder), and hard-evict a lease-dead host once the eviction
        deadline passes. Returns None when preemption interrupts."""
        cfg = self.cfg
        started = cfg.clock.monotonic()
        evict_deadlines: Dict[str, Deadline] = {}
        last_stall: Tuple = ()
        while True:
            t_try = cfg.clock.monotonic()
            red = self.coord.try_reduce(round_)
            if red is not None:
                now = cfg.clock.monotonic()
                reduce_s = now - t_try
                waited = now - started
                if waited > cfg.poll_s:
                    round_wait_seconds_histogram(self.registry).observe(
                        waited, host=cfg.host)
                # the round timeline's wait/reduce decomposition: wait =
                # blocked polling for peers (attributed to the missing
                # hosts), reduce = the successful mean + digest check
                self._record_span("wait", waited - reduce_s,
                                  round=round_,
                                  waiting_on=list(last_stall))
                self._record_span("reduce", reduce_s, round=round_)
                return red
            if self._stop_requested():
                return None
            self.coord.heartbeat(self._round)
            view = self.coord.fleet_view()
            members = self.coord.members_for_round(round_)
            missing = tuple(h for h in members
                            if h not in self.coord.published_hosts(round_))
            if missing != last_stall:
                last_stall = missing
                _flight.record(
                    "elastic_stall", host=cfg.host, round=int(round_),
                    waiting_on=list(missing),
                    waited_s=round(cfg.clock.monotonic() - started, 3))
            for h in missing:
                if h == cfg.host:
                    raise ElasticProtocolError(
                        f"round {round_}: waiting on own contribution")
                if view.get(h, {}).get("alive", False):
                    evict_deadlines.pop(h, None)
                    continue
                dl = evict_deadlines.get(h)
                if dl is None:
                    dl = evict_deadlines[h] = Deadline(
                        cfg.evict_after_s, cfg.clock)
                if dl.expired:
                    self.coord.hard_evict(h, blocked_round=round_)
                    evict_deadlines.pop(h, None)
            self._pet()
            cfg.clock.sleep(cfg.poll_s)

    def _apply_correction(self, round_: int,
                          reduction: Sequence[np.ndarray]) -> None:
        own = self._own_deltas.pop(round_, None)
        if own is None and round_ >= self._member_from:
            # a resumed incarnation recovers its own published delta
            # from the ledger (the in-memory copy died with the process)
            own = self.coord.contribution(round_, self.cfg.host)
        leaves = _net_param_leaves(self.net)
        out = []
        for i, p in enumerate(leaves):
            corr = reduction[i] - (own[i].astype(np.float64)
                                   if own is not None else 0.0)
            out.append((p.astype(np.float64) + corr).astype(p.dtype))
        _set_net_params_from_leaves(self.net, out)

    # -- the round -----------------------------------------------------

    def _run_round(self, batch_fn: Callable, r: int) -> bool:
        cfg = self.cfg
        t0 = cfg.clock.monotonic()
        self._round = r
        self._ctx.update(round=r, phase="steps", waiting_on=[])
        self._round_spans = []
        replay = cfg.host in self.coord.published_hosts(r)
        with self._span("elastic.round", round=r, replay=bool(replay)):
            self._held = self._capture()
            if cfg.checkpoint_every_rounds and \
                    r % cfg.checkpoint_every_rounds == 0:
                self._write_snapshot(self._held)
            self.coord.heartbeat(r)
            p_before = _net_param_leaves(self.net)
            with self._span("local_steps", round=r,
                            steps=cfg.steps_per_round):
                for step in range(cfg.steps_per_round):
                    it = getattr(self.net, "iteration_count", 0)
                    _faults.check("training.step",
                                  {"iteration": it, "round": r,
                                   "host": cfg.host, "elastic": True})
                    if self._stop_requested():
                        return False    # round restarts from _held
                    batch = batch_fn(r, step)
                    self.stepper.fit_batch(*batch)
                    self._pet()
                    self.coord.heartbeat(r)   # rate-limited; bounds the
                                              # gap to one step even in
                                              # long rounds
            delta = [a - b for a, b in zip(_net_param_leaves(self.net),
                                           p_before)]
            with self._span("publish", round=r):
                self.coord.publish_contribution(r, delta)
            self._own_deltas[r] = delta
            self.coord.heartbeat(r + 1, force=True)
            j = r - cfg.max_staleness
            while self._applied_next <= j:
                self._ctx.update(phase="await_reduce", waiting_on=[])
                red = self._await_reduce(self._applied_next)
                if red is None:
                    return False
                with self._span("apply", round=self._applied_next):
                    self._apply_correction(self._applied_next, red)
                self._applied_next += 1
            dt = cfg.clock.monotonic() - t0
            rounds_counter(self.registry).inc(host=cfg.host)
            round_seconds_histogram(self.registry).observe(dt,
                                                           host=cfg.host)
            view = self.coord.fleet_view()
            live_rounds = [v["round"] for h, v in view.items()
                           if v["alive"] and not v["done"] and h != cfg.host
                           and v["round"] >= 0]
            staleness_gauge(self.registry).set(
                (r + 1) - min(live_rounds) if live_rounds else 0,
                host=cfg.host)
            _flight.record("elastic_round", host=cfg.host, round=r,
                           seconds=round(dt, 4), steps=cfg.steps_per_round,
                           replay=bool(replay))
        # export the finished round's spans next to its REDUCE record —
        # the timeline collector's per-host input for this round
        self.coord.publish_trace(r, [s.to_dict()
                                     for s in self._round_spans])
        return True

    # -- finish: tail flush + digest barrier ---------------------------

    def _finish(self, rounds: int) -> None:
        cfg = self.cfg
        while self._applied_next < rounds:
            self._ctx.update(phase="tail_flush",
                             round=self._applied_next)
            red = self._await_reduce(self._applied_next)
            if red is None:
                return
            self._apply_correction(self._applied_next, red)
            self._applied_next += 1
        # canonical finalization: every host rebuilds p0 + Σ R(j) with
        # one op order. The incremental chains above land on the same
        # value only up to float non-associativity ((p0+d)+(R-d) is not
        # bitwise p0+R); the barrier digest needs the exact same bits.
        acc = [p.astype(np.float64) for p in self._p0]
        for j in range(rounds):
            red = self.coord.try_reduce(j)
            if red is None:
                raise ElasticProtocolError(
                    f"round {j} not reduced at finalization")
            acc = [a + r_ for a, r_ in zip(acc, red)]
        _set_net_params_from_leaves(
            self.net, [a.astype(p.dtype) for a, p in zip(acc, self._p0)])
        from ..util.durable import params_digest
        import jax
        digest = params_digest(jax.device_get(self.net.params), None, 0)
        self.final_digest = digest
        self.coord.publish_final(digest)
        self.coord.heartbeat(rounds, status="done", force=True)
        self.agreed = agree_on_digest(
            digest, allgather=self._final_allgather(rounds))
        _flight.record("elastic_final", host=cfg.host,
                       digest=digest[:12], agreed=self.agreed)
        if not self.agreed:
            raise ElasticProtocolError(
                "fleet digest disagreement at the final barrier — a host "
                "diverged from the deterministic round chain")
        self._write_snapshot(self._capture(kind="final"))

    def _final_allgather(self, rounds: int):
        """A store-backed allgather for ``agree_on_digest``: wait for
        every round-``rounds`` member's final digest (hard-evicting a
        host that dies before the barrier), then return them stacked in
        fleet order."""
        cfg = self.cfg

        def gather(local: np.ndarray) -> np.ndarray:
            deadlines: Dict[str, Deadline] = {}
            while True:
                members = [h for h in cfg.fleet
                           if self.coord.member_at(h, rounds)]
                digests = {h: self.coord.final_digest_of(h)
                           for h in members}
                missing = [h for h in members if digests[h] is None]
                if not missing:
                    rows = [np.frombuffer(bytes.fromhex(digests[h]),
                                          dtype=np.uint8)
                            for h in members]
                    return np.stack(rows)
                view = self.coord.fleet_view()
                for h in missing:
                    if h == cfg.host or view.get(h, {}).get("alive"):
                        deadlines.pop(h, None)
                        continue
                    dl = deadlines.setdefault(
                        h, Deadline(cfg.evict_after_s, cfg.clock))
                    if dl.expired:
                        self.coord.hard_evict(h, blocked_round=rounds)
                        deadlines.pop(h, None)
                self.coord.heartbeat(rounds, status="done")
                self._pet()
                cfg.clock.sleep(cfg.poll_s)

        return gather

    # -- fit -----------------------------------------------------------

    def fit(self, batch_fn: Callable, *, rounds: int):
        """Train ``rounds`` elastic sync rounds (resuming from the
        durable round cursor when restored). Returns the network."""
        from ..util.durable import PreemptionHandler, StepWatchdog
        if rounds < 1:
            raise ValueError("rounds must be >= 1")
        self._preemption = (PreemptionHandler().install()
                            if self.handle_signals else None)
        self._watchdog = None
        if self.watchdog_s:
            self._watchdog = StepWatchdog(
                self.watchdog_s, registry=self.registry,
                context_provider=lambda: {
                    **_faults.seam_context(),
                    "elastic": dict(self._ctx)})
            self._watchdog.arm()
        # the host's root span: parented on the fleet trace the spawning
        # scheduler handed us (DL4JTPU_TRACEPARENT), so every member's
        # round spans share one trace id and merge into one timeline
        fit_ctx = self.tracer.span(
            "elastic.fit", parent=_tracing.env_context(),
            attributes={"rounds": rounds,
                        "incarnation": self.coord.incarnation,
                        "resumed": self.resumed})
        fit_span = fit_ctx.__enter__()
        fit_exc: Tuple = (None, None, None)
        try:
            self._plan_membership(rounds)
            # rejoin-as-new flips `resumed` inside _plan_membership —
            # re-stamp so the exported root span reports how this
            # incarnation actually started
            fit_span.set_attribute("resumed", self.resumed)
            self.coord.heartbeat(self._round, force=True)
            self.coord.fleet_view()
            # catch up the reduction history this chain has not yet
            # folded in (rejoined-as-new members start at p0 and need
            # every R(j) up to their first round's base)
            while self._applied_next < self._round - self.cfg.max_staleness:
                self._ctx.update(phase="history_catchup",
                                 round=self._applied_next)
                red = self._await_reduce(self._applied_next)
                if red is None:
                    break
                self._apply_correction(self._applied_next, red)
                self._applied_next += 1
            r = self._round
            while r < rounds and not self._stop_requested():
                if not self._run_round(batch_fn, r):
                    break
                r += 1
                self._round = r
            if not self._stop_requested():
                self._finish(rounds)
            if self._stop_requested():
                # preempted mid-rounds OR mid-finish: round-start state
                # is the recovery point — mid-round progress and the
                # tail flush are recomputed deterministically on resume
                self.preempted = True
                self._write_snapshot(self._held)
                _flight.record("elastic_preempted", host=self.cfg.host,
                               round=self._round)
        except BaseException:
            # captured explicitly, NOT via sys.exc_info() in the
            # finally — a caller invoking fit() from inside its own
            # `except` block (the restart-after-preemption flow) has a
            # live outer exception that would falsely mark a clean
            # run's root span as error
            fit_exc = sys.exc_info()
            raise
        finally:
            fit_ctx.__exit__(*fit_exc)
            if self._watchdog is not None:
                self._watchdog.disarm()
            if self._preemption is not None:
                self._preemption.uninstall()
        return self.net
