"""Expert parallelism: mixture-of-experts FFN sharded over an ``ep`` axis.

No reference analog (SURVEY §2.9: EP = NO) — north-star extension. Design:
the dense-dispatch MoE formulation (every expert computes every token;
top-k gates zero the unused results) expressed as einsums over a stacked
expert dimension, with expert parameters sharded over the ``ep`` mesh axis
via GSPMD — XLA partitions the einsums and inserts the cross-expert
reduce. Dense dispatch trades FLOPs for static shapes: no scatter/gather,
no capacity overflow, fully compiler-friendly — the right starting point
on TPU (sparse all-to-all dispatch is a kernel-level optimization on top,
not a different architecture).

Includes the standard auxiliary load-balancing loss (mean gate fraction ×
mean top-k assignment fraction, summed over experts and scaled by E).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng
from .dsl_trainer import ShardedDSLTrainerBase

Pytree = Any


def init_moe_params(key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32) -> Pytree:
    """Router + stacked expert FFNs ([E, ...] leading expert dim)."""
    k_r, k_1, k_2 = jax.random.split(key, 3)
    scale1 = 1.0 / np.sqrt(d_model)
    scale2 = 1.0 / np.sqrt(d_hidden)
    return {
        "router": (jax.random.normal(k_r, (d_model, n_experts), dtype)
                   * scale1),
        "w1": jax.random.normal(k_1, (n_experts, d_model, d_hidden),
                                dtype) * scale1,
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "w2": jax.random.normal(k_2, (n_experts, d_hidden, d_model),
                                dtype) * scale2,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def moe_apply(params: Pytree, x: jax.Array, *, top_k: int = 2):
    """[b, d] -> ([b, d], aux_loss). Dense dispatch over all experts."""
    e = params["w1"].shape[0]
    logits = x @ params["router"]                        # [b, E]
    gates = jax.nn.softmax(logits, axis=-1)
    if top_k < e:
        # lax.top_k breaks ties deterministically (lowest index), so
        # EXACTLY top_k experts fire even for degenerate uniform gates
        _, idx = jax.lax.top_k(gates, top_k)             # [b, k]
        keep = jax.nn.one_hot(idx, e).sum(axis=1) > 0    # [b, E]
        masked = jnp.where(keep, gates, 0.0)
        weights = masked / jnp.maximum(
            masked.sum(-1, keepdims=True), 1e-9)         # renormalized
    else:
        keep = jnp.ones_like(gates, bool)
        weights = gates
    h = jax.nn.relu(jnp.einsum("bd,edh->ebh", x, params["w1"])
                    + params["b1"][:, None, :])
    y_e = (jnp.einsum("ebh,ehd->ebd", h, params["w2"])
           + params["b2"][:, None, :])
    y = jnp.einsum("be,ebd->bd", weights, y_e)
    # Shazeer-style load-balancing aux: E * sum_e mean_gate_e * mean_keep_e
    aux = e * jnp.sum(jnp.mean(gates, axis=0)
                      * jnp.mean(keep.astype(gates.dtype), axis=0))
    return y, aux


def moe_param_shardings(mesh: Mesh, axis: str = "ep") -> Pytree:
    """NamedShardings: expert-stacked tensors split over ``axis``, router
    replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P(axis, None, None)),
        "b1": NamedSharding(mesh, P(axis, None)),
        "w2": NamedSharding(mesh, P(axis, None, None)),
        "b2": NamedSharding(mesh, P(axis, None)),
    }


class ExpertParallelTrainer:
    """Train an MoE FFN with experts sharded over the ``ep`` mesh axis.

    Regression-style head: ``loss = mse(moe(x), y) + aux_weight * aux``.
    The jitted step runs under GSPMD — each device holds E/ep experts and
    XLA inserts the cross-expert collectives.
    """

    def __init__(self, d_model: int, d_hidden: int, n_experts: int,
                 mesh: Mesh, *, axis: str = "ep", top_k: int = 2,
                 learning_rate: float = 0.05, aux_weight: float = 0.01,
                 seed: int = 0):
        if n_experts % mesh.shape[axis]:
            raise ValueError(
                f"n_experts={n_experts} not divisible by mesh axis "
                f"{axis!r} size {mesh.shape[axis]}")
        self.mesh = mesh
        self.top_k = int(top_k)
        self.lr = float(learning_rate)
        self.aux_weight = float(aux_weight)
        params = init_moe_params(_rng.key(seed), d_model, d_hidden,
                                 n_experts)
        shardings = moe_param_shardings(mesh, axis)
        self.params = {k: jax.device_put(v, shardings[k])
                       for k, v in params.items()}

        top_k_ = self.top_k
        aux_w = self.aux_weight
        lr = self.lr

        def loss_fn(params, x, y):
            out, aux = moe_apply(params, x, top_k=top_k_)
            return jnp.mean((out - y) ** 2) + aux_w * aux

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(params, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
            return params, loss

        self._step = step
        self._apply = jax.jit(
            functools.partial(moe_apply, top_k=top_k_))

    def forward(self, x):
        y, _ = self._apply(self.params, jnp.asarray(x))
        return y

    def fit_batch(self, x, y) -> jax.Array:
        self.params, loss = self._step(self.params, jnp.asarray(x),
                                       jnp.asarray(y))
        return loss


# --------------------------------------------------------------------------
# expert parallelism for DSL models (MoELayer vertices)
# --------------------------------------------------------------------------


def expert_param_specs(net, axis: str = "ep") -> Pytree:
    """PartitionSpec pytree for net.params: expert-stacked MoELayer params
    split over ``axis`` on their leading E dim, everything else
    replicated."""
    from ..nn.conf.moe import MoELayer

    def layer_of(key):
        if hasattr(net, "topo_order"):
            v = net.conf.vertices.get(key)
            return getattr(v, "layer", None)
        idx = int(key.split("_")[-1])
        return net.layers[idx]

    specs = {}
    for key, lp in net.params.items():
        layer = layer_of(key)
        if isinstance(layer, MoELayer):
            specs[key] = {
                name: (P(axis, *([None] * (p.ndim - 1)))
                       if name != "router" else P())
                for name, p in lp.items()}
        else:
            specs[key] = {name: P() for name in lp}
    return specs



def expert_param_shardings(net, mesh: Mesh, axis: str = "ep") -> Pytree:
    """Validated NamedSharding tree for a net's MoELayer expert params:
    raises if the net has no MoE vertices or an expert count does not
    divide the mesh axis. ONE implementation for every trainer that
    composes expert sharding."""
    if axis not in mesh.axis_names:
        raise ValueError(f"expert axis {axis!r} not in mesh "
                         f"{mesh.axis_names}")
    specs = expert_param_specs(net, axis)
    if not any(sp != P() for lp in specs.values() for sp in lp.values()):
        raise ValueError("no MoELayer params found to shard — expert "
                         "parallelism needs MoE vertices in the net")
    n_exp = {tuple(p.shape)[0] for key, lp in net.params.items()
             for name, p in lp.items()
             if specs[key][name] != P() and name != "router"}
    for e in n_exp:
        if e % mesh.shape[axis]:
            raise ValueError(
                f"n_experts={e} not divisible by mesh axis "
                f"{axis!r} size {mesh.shape[axis]}")
    return jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), specs,
        is_leaf=lambda x: isinstance(x, P))


class ExpertParallelGraphTrainer(ShardedDSLTrainerBase):
    """Expert-parallel training for DSL models containing ``MoELayer``s:
    expert-stacked params are sharded over the ``ep`` mesh axis (each
    device holds E/ep experts; XLA partitions the dense-dispatch einsums
    and inserts the cross-expert reduce), everything else replicated,
    batch optionally data-parallel over ``batch_axis``. Shares the full
    sharded-trainer contract (masks, TBPTT chunk rejection, output())
    with ``SequenceParallelGraphTrainer`` via ``ShardedDSLTrainerBase``.
    """

    _api = "ExpertParallelGraphTrainer"

    def __init__(self, net, mesh: Mesh, *, axis: str = "ep",
                 batch_axis: Optional[str] = None,
                 skip_nonfinite_budget: Optional[int] = None):
        if net.params is None:
            net.init()
        self.axis = axis
        shardings = expert_param_shardings(net, mesh, axis)
        self._build(net, mesh,
                    x_spec=P(batch_axis), mask_spec=P(batch_axis),
                    batch_axis=batch_axis, param_shardings=shardings,
                    skip_nonfinite_budget=skip_nonfinite_budget)
