"""Device-mesh bootstrap.

The analog of the reference's device discovery in ``ParallelWrapper``
(worker count = ``Nd4j.getAffinityManager().getNumberOfDevices()``); here a
``jax.sharding.Mesh`` over the local (or all) devices, with named axes that
the rest of the framework shards over:

  - ``data``  — batch dimension (dp)
  - ``model`` — tensor-parallel dimension (tp), used by parallel/tensor.py
  - ``seq``   — sequence/context-parallel dimension (sp), used by ring attention
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def mesh_devices(n: Optional[int] = None):
    """First `n` available devices (default: all)."""
    devs = jax.devices()
    if n is not None:
        if n > len(devs):
            raise ValueError(
                f"requested {n} devices but only {len(devs)} available "
                f"({[d.platform for d in devs[:3]]}...); for CPU-mesh tests "
                "set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "before JAX initializes")
        devs = devs[:n]
    return devs


def create_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Mesh with named axes, e.g. ``create_mesh({"data": 4, "model": 2})``."""
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    total = int(np.prod(shape))
    devs = devices if devices is not None else mesh_devices(total)
    if len(devs) != total:
        raise ValueError(f"mesh {axes} needs {total} devices, got {len(devs)}")
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, names)


def data_parallel_mesh(n: Optional[int] = None, devices=None) -> Mesh:
    """1-D ``data`` mesh over n devices (default all local devices)."""
    devs = devices if devices is not None else mesh_devices(n)
    return Mesh(np.asarray(devs), ("data",))
