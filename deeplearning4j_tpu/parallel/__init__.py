"""Distributed training over TPU meshes.

Parity: reference single-node multi-device ``ParallelWrapper``
(``deeplearning4j-core/.../parallelism/ParallelWrapper.java:37-204``) and the
Spark ``ParameterAveragingTrainingMaster``
(``dl4j-spark/.../impl/paramavg/ParameterAveragingTrainingMaster.java:340``).

TPU-native design — two modes, both expressed as XLA SPMD programs over a
``jax.sharding.Mesh`` (no worker threads, no parameter shipping over TCP):

- **sync** (default, ``averaging_frequency=1``): ONE jitted train step with the
  batch sharded over the ``data`` mesh axis and params replicated. XLA inserts
  the gradient all-reduce over ICI automatically. This is strictly stronger
  than the reference's averaging-every-N (equivalent to N=1 at far lower
  cost than its param shipping).
- **local-SGD** (``averaging_frequency=k > 1``): per-replica parameter copies
  (stacked, sharded over ``data``) each step independently on their batch
  shard via ``shard_map``; every k steps params+updater state are averaged
  with ``pmean`` — the exact semantics of ``ParallelWrapper.java:145``
  (``Nd4j.averageAndPropagate``) and
  ``ParameterAveragingTrainingMaster.java:763-832``.
"""

from .distributed import (global_mesh, host_local_batch,
                          host_replicated_batch, initialize,
                          is_initialized, process_count, process_index)
from .elastic import (CoordinationStore, ElasticConfig, ElasticTrainer,
                      FileCoordinationStore, InMemoryCoordinationStore)
from .expert import ExpertParallelGraphTrainer, ExpertParallelTrainer
from .mesh import create_mesh, data_parallel_mesh, mesh_devices
from .pipeline import GraphPipelineTrainer, PipelineParallelTrainer
from .sequence import SequenceParallelGraphTrainer
from .tensor import TensorParallelGraphTrainer, TensorParallelTrainer
from .training_master import (ElasticTrainingMaster,
                              ParameterAveragingTrainingMaster,
                              SyncTrainingMaster, Trainer, TrainingMaster)
from .wrapper import ParallelWrapper

__all__ = ["ParallelWrapper", "create_mesh", "data_parallel_mesh",
           "mesh_devices", "initialize", "is_initialized", "global_mesh",
           "host_local_batch", "host_replicated_batch", "process_count",
           "process_index", "TrainingMaster", "Trainer",
           "SyncTrainingMaster", "ParameterAveragingTrainingMaster",
           "ElasticTrainingMaster", "ElasticTrainer", "ElasticConfig",
           "CoordinationStore", "FileCoordinationStore",
           "InMemoryCoordinationStore", "TensorParallelTrainer",
           "PipelineParallelTrainer", "GraphPipelineTrainer",
           "SequenceParallelGraphTrainer", "ExpertParallelTrainer",
           "ExpertParallelGraphTrainer", "TensorParallelGraphTrainer"]
