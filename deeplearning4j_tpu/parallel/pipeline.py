"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

No reference analog (SURVEY §2.9: PP = NO) — north-star extension. Design
is the standard TPU shift-register schedule (scaling-book style): a stack
of S identical blocks, one per device along the ``pp`` axis, processes M
microbatches in M+S-1 ticks; activations hop stage→stage over
``lax.ppermute`` inside ``shard_map``, and autodiff through the permute
gives exact pipeline-parallel gradients (the transpose of a shift forward
is a shift backward). Stage parameters live only on their stage's device —
memory scales 1/S, unlike a replicated fake pipeline.

Scope: homogeneous stacks (every stage runs the same ``block_fn`` with its
own parameters) — the shape pipeline parallelism is actually used for
(transformer/MLP blocks). Heterogeneous stages belong to tensor/data
parallelism or model surgery, not this schedule.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng

Pytree = Any
BlockFn = Callable[[Pytree, jax.Array], jax.Array]


def make_pipeline_forward(mesh: Mesh, axis: str, block_fn: BlockFn,
                          n_stages: int, n_micro: int):
    """Build ``fn(stacked_params, xm) -> ym``.

    ``stacked_params``: pytree with leading stage axis [S, ...], sharded
    over ``axis``. ``xm``: microbatched input [M, b, ...] (replicated).
    Returns [M, b, ...] — the last stage's outputs, replicated.
    """
    if mesh.shape[axis] != n_stages:
        raise ValueError(
            f"mesh axis {axis!r} has size {mesh.shape[axis]}, "
            f"need n_stages={n_stages}")
    S, M = n_stages, n_micro
    perm = [(i, (i + 1) % S) for i in range(S)]

    def staged(params_blk, xm):
        local = jax.tree_util.tree_map(lambda a: a[0], params_blk)
        s = lax.axis_index(axis)

        def tick(carry, t):
            inflight, outs = carry
            m_in = jnp.clip(t, 0, M - 1)
            first = lax.dynamic_index_in_dim(xm, m_in, 0, keepdims=False)
            x_in = jnp.where(s == 0, first, inflight)
            y = block_fn(local, x_in)
            nxt = lax.ppermute(y, axis, perm)
            # the value reaching stage S-1 at tick t is microbatch t-(S-1);
            # masked select (not lax.cond: branches would differ in
            # mesh-variance type under shard_map's replication tracking)
            m_out = jnp.clip(t - (S - 1), 0, M - 1)
            write = jnp.logical_and(s == S - 1, t >= S - 1)
            updated = lax.dynamic_update_index_in_dim(outs, y, m_out, 0)
            outs = jnp.where(write, updated, outs)
            return (nxt, outs), None

        # carries become device-varying inside the loop (ppermute / masked
        # writes), so their initial values must carry the same
        # mesh-variance type
        inflight0 = lax.pcast(jnp.zeros_like(xm[0]), axis, to="varying")
        outs0 = lax.pcast(jnp.zeros_like(xm), axis, to="varying")
        (_, outs), _ = lax.scan(tick, (inflight0, outs0),
                                jnp.arange(M + S - 1))
        # replicate the last stage's outputs to every device
        return lax.psum(jnp.where(s == S - 1, outs, jnp.zeros_like(outs)),
                        axis)

    def fn(stacked_params, xm):
        in_specs = (jax.tree_util.tree_map(lambda _: P(axis),
                                           stacked_params), P())
        return shard_map(staged, mesh=mesh, in_specs=in_specs,
                         out_specs=P())(stacked_params, xm)

    return fn


class PipelineParallelTrainer:
    """Train a stack of S identical blocks pipelined over ``axis``.

    ``layer``: a framework layer config (e.g. ``DenseLayer(n_in=d, n_out=d)``)
    whose ``apply(params, x, ...)`` is pure and shape-preserving; its
    parameters are initialized per stage and stacked [S, ...]. The loss
    head is a plain callable ``loss_fn(y, targets) -> scalar`` evaluated on
    the final stage's (replicated) outputs.
    """

    def __init__(self, layer, n_stages: int, mesh: Mesh, *,
                 axis: str = "pp", n_micro: Optional[int] = None,
                 learning_rate: float = 0.01, loss: str = "mse",
                 seed: int = 0, policy=None):
        from .. import losses as _losses

        self.layer = layer
        self.mesh = mesh
        self.axis = axis
        self.S = int(n_stages)
        self.M = int(n_micro if n_micro is not None else n_stages)
        self.lr = float(learning_rate)

        def block_fn(p, x):
            y, _ = layer.apply(p, x, state=None, train=False, rng=None,
                               policy=policy)
            return y

        # build first: validates n_stages against the mesh axis BEFORE any
        # sharding (a mismatched device_put fails far less readably)
        fwd = make_pipeline_forward(mesh, axis, block_fn, self.S, self.M)

        key = _rng.key(seed)
        per_stage = [layer.init_params(_rng.fold_name(key, f"stage_{i}"),
                                       policy)
                     for i in range(self.S)]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *per_stage)
        self.params = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                a, NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1))))),
            stacked)
        loss_elem = _losses.get(loss)

        def loss_fn(params, xm, ym):
            out = fwd(params, xm)
            per = loss_elem(ym, out, "identity")
            return jnp.mean(per)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(params, xm, ym):
            loss_val, grads = jax.value_and_grad(loss_fn)(params, xm, ym)
            params = jax.tree_util.tree_map(
                lambda p, g: p - self.lr * g, params, grads)
            return params, loss_val

        self._fwd = jax.jit(fwd)
        self._step = step

    def _microbatch(self, x) -> jax.Array:
        x = jnp.asarray(x)
        b = x.shape[0]
        if b % self.M:
            raise ValueError(f"batch {b} not divisible by n_micro={self.M}")
        return x.reshape((self.M, b // self.M) + x.shape[1:])

    def forward(self, x):
        """Pipelined forward; returns [b, ...] on the host layout."""
        ym = self._fwd(self.params, self._microbatch(x))
        return ym.reshape((-1,) + ym.shape[2:])

    def fit_batch(self, x, y) -> jax.Array:
        xm = self._microbatch(x)
        ym = self._microbatch(y)
        self.params, loss = self._step(self.params, xm, ym)
        return loss
