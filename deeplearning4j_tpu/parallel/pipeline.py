"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

No reference analog (SURVEY §2.9: PP = NO) — north-star extension. Design
is the standard TPU shift-register schedule (scaling-book style): a stack
of S identical blocks, one per device along the ``pp`` axis, processes M
microbatches in M+S-1 ticks; activations hop stage→stage over
``lax.ppermute`` inside ``shard_map``, and autodiff through the permute
gives exact pipeline-parallel gradients (the transpose of a shift forward
is a shift backward). Stage parameters live only on their stage's device —
memory scales 1/S, unlike a replicated fake pipeline.

Scope: homogeneous stacks (every stage runs the same ``block_fn`` with its
own parameters) — the shape pipeline parallelism is actually used for
(transformer/MLP blocks). Heterogeneous stages belong to tensor/data
parallelism or model surgery, not this schedule.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng

Pytree = Any
BlockFn = Callable[[Pytree, jax.Array], jax.Array]


def make_pipeline_forward(mesh: Mesh, axis: str, block_fn: BlockFn,
                          n_stages: int, n_micro: int,
                          batch_axis: Optional[str] = None):
    """Build ``fn(stacked_params, xm) -> ym``.

    ``stacked_params``: pytree with leading stage axis [S, ...], sharded
    over ``axis``. ``xm``: microbatched input [M, b, ...] (replicated, or
    with the per-microbatch batch dim sharded over ``batch_axis`` for 2-D
    dp x pp meshes — each dp slice then runs its own pipeline).
    Returns [M, b, ...] — the last stage's outputs, with the same batch
    sharding.
    """
    if mesh.shape[axis] != n_stages:
        raise ValueError(
            f"mesh axis {axis!r} has size {mesh.shape[axis]}, "
            f"need n_stages={n_stages}")
    S, M = n_stages, n_micro
    perm = [(i, (i + 1) % S) for i in range(S)]

    def staged(params_blk, xm):
        local = jax.tree_util.tree_map(lambda a: a[0], params_blk)
        s = lax.axis_index(axis)

        def tick(carry, t):
            inflight, outs = carry
            m_in = jnp.clip(t, 0, M - 1)
            first = lax.dynamic_index_in_dim(xm, m_in, 0, keepdims=False)
            x_in = jnp.where(s == 0, first, inflight)
            y = block_fn(local, x_in)
            nxt = lax.ppermute(y, axis, perm)
            # the value reaching stage S-1 at tick t is microbatch t-(S-1);
            # masked select (not lax.cond: branches would differ in
            # mesh-variance type under shard_map's replication tracking)
            m_out = jnp.clip(t - (S - 1), 0, M - 1)
            write = jnp.logical_and(s == S - 1, t >= S - 1)
            updated = lax.dynamic_update_index_in_dim(outs, y, m_out, 0)
            outs = jnp.where(write, updated, outs)
            return (nxt, outs), None

        # carries become device-varying inside the loop (ppermute / masked
        # writes), so their initial values must carry the same
        # mesh-variance type; older jax has no varying-type tracking (and
        # no lax.pcast), so the zeros pass through untyped there
        if hasattr(lax, "pcast"):
            inflight0 = lax.pcast(jnp.zeros_like(xm[0]), axis, to="varying")
            outs0 = lax.pcast(jnp.zeros_like(xm), axis, to="varying")
        else:
            inflight0 = jnp.zeros_like(xm[0])
            outs0 = jnp.zeros_like(xm)
        (_, outs), _ = lax.scan(tick, (inflight0, outs0),
                                jnp.arange(M + S - 1))
        # replicate the last stage's outputs to every device
        return lax.psum(jnp.where(s == S - 1, outs, jnp.zeros_like(outs)),
                        axis)

    x_spec = P(None, batch_axis)

    def fn(stacked_params, xm):
        in_specs = (jax.tree_util.tree_map(lambda _: P(axis),
                                           stacked_params), x_spec)
        return shard_map(staged, mesh=mesh, in_specs=in_specs,
                         out_specs=x_spec)(stacked_params, xm)

    return fn


class PipelineParallelTrainer:
    """Train a stack of S identical blocks pipelined over ``axis``.

    ``layer``: a framework layer config (e.g. ``DenseLayer(n_in=d, n_out=d)``)
    whose ``apply(params, x, ...)`` is pure and shape-preserving; its
    parameters are initialized per stage and stacked [S, ...]. The loss
    head is a plain callable ``loss_fn(y, targets) -> scalar`` evaluated on
    the final stage's (replicated) outputs.
    """

    def __init__(self, layer, n_stages: int, mesh: Mesh, *,
                 axis: str = "pp", n_micro: Optional[int] = None,
                 learning_rate: float = 0.01, loss: str = "mse",
                 seed: int = 0, policy=None):
        from .. import losses as _losses

        self.layer = layer
        self.mesh = mesh
        self.axis = axis
        self.S = int(n_stages)
        self.M = int(n_micro if n_micro is not None else n_stages)
        self.lr = float(learning_rate)

        def block_fn(p, x):
            y, _ = layer.apply(p, x, state=None, train=False, rng=None,
                               policy=policy)
            return y

        # build first: validates n_stages against the mesh axis BEFORE any
        # sharding (a mismatched device_put fails far less readably)
        fwd = make_pipeline_forward(mesh, axis, block_fn, self.S, self.M)

        key = _rng.key(seed)
        per_stage = [layer.init_params(_rng.fold_name(key, f"stage_{i}"),
                                       policy)
                     for i in range(self.S)]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *per_stage)
        self.params = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                a, NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1))))),
            stacked)
        loss_elem = _losses.get(loss)

        def loss_fn(params, xm, ym):
            out = fwd(params, xm)
            per = loss_elem(ym, out, "identity")
            return jnp.mean(per)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(params, xm, ym):
            loss_val, grads = jax.value_and_grad(loss_fn)(params, xm, ym)
            params = jax.tree_util.tree_map(
                lambda p, g: p - self.lr * g, params, grads)
            return params, loss_val

        self._fwd = jax.jit(fwd)
        self._step = step

    def _microbatch(self, x) -> jax.Array:
        x = jnp.asarray(x)
        b = x.shape[0]
        if b % self.M:
            raise ValueError(f"batch {b} not divisible by n_micro={self.M}")
        return x.reshape((self.M, b // self.M) + x.shape[1:])

    def forward(self, x):
        """Pipelined forward; returns [b, ...] on the host layout."""
        ym = self._fwd(self.params, self._microbatch(x))
        return ym.reshape((-1,) + ym.shape[2:])

    def fit_batch(self, x, y) -> jax.Array:
        xm = self._microbatch(x)
        ym = self._microbatch(y)
        self.params, loss = self._step(self.params, xm, ym)
        return loss


# --------------------------------------------------------------------------
# pipeline parallelism for DSL ComputationGraphs
# --------------------------------------------------------------------------


def _partition_pipeline(conf, pattern: str):
    """Cut a graph's topo order into (prologue, [(block_id, [vertices])],
    epilogue) by the repeated-block naming pattern. Validates the cut is
    actually pipeline-shaped: contiguous blocks, single external input per
    block (the previous block's output), structurally identical stages."""
    topo = conf.topological_order()
    pre: List[str] = []
    blocks: List[Tuple[str, List[str]]] = []
    post: List[str] = []
    for name in topo:
        m = re.match(pattern, name)
        if m:
            if post:
                raise ValueError(
                    f"block vertex {name!r} appears after non-block "
                    f"vertices {post} in topological order — blocks must "
                    "be contiguous to pipeline")
            bid = m.group(1)
            if not blocks or blocks[-1][0] != bid:
                if any(b == bid for b, _ in blocks):
                    raise ValueError(
                        f"block {bid!r} is interleaved with other blocks "
                        "in topological order — cannot pipeline")
                blocks.append((bid, []))
            blocks[-1][1].append(name)
        elif not blocks:
            pre.append(name)
        else:
            post.append(name)
    if not blocks:
        raise ValueError(
            f"no vertices match block pattern {pattern!r}; name repeated "
            "blocks like 'blk0_...' (models/transformer.py style) or pass "
            "block_pattern")
    # structural homogeneity: same suffix sequence AND identical vertex
    # configs in every block — stage s's params run through block 0's
    # vertex objects, so a config drift (e.g. different activation in
    # same-named vertices) would train silently wrong
    def suffix(bid, name):
        return name[len(bid):]
    sig0 = [suffix(blocks[0][0], n) for n in blocks[0][1]]
    for bid, names in blocks[1:]:
        sig = [suffix(bid, n) for n in names]
        if sig != sig0:
            raise ValueError(
                f"block {bid!r} has structure {sig}, expected {sig0} — "
                "stages must be homogeneous to ride the pipeline schedule")
        for n0, n in zip(blocks[0][1], names):
            if conf.vertices[n] != conf.vertices[n0]:
                raise ValueError(
                    f"vertex {n!r} config differs from template {n0!r} — "
                    "stages must be homogeneous to ride the pipeline "
                    "schedule")
    # single external input per block == the previous block's output (or
    # the network input, for graphs whose first block has no prologue)
    prev_out = pre[-1] if pre else conf.network_inputs[0]
    for bid, names in blocks:
        in_block = set(names)
        externals = {src for n in names
                     for src in conf.vertex_inputs[n]
                     if src not in in_block}
        if externals != {prev_out}:
            raise ValueError(
                f"block {bid!r} reads {sorted(externals)} from outside the "
                f"block; a pipeline stage may only read its input "
                f"({prev_out!r})")
        prev_out = names[-1]
    # epilogue may read the last block's output and other epilogue vertices
    allowed = set(post) | {prev_out} | set(conf.network_inputs)
    for n in post:
        for src in conf.vertex_inputs[n]:
            if src not in allowed:
                raise ValueError(
                    f"epilogue vertex {n!r} reads {src!r} from inside the "
                    "pipelined region — cannot pipeline this graph")
    return pre, blocks, post


class GraphPipelineTrainer:
    """GPipe pipeline parallelism for a DSL ``ComputationGraph`` with
    repeated homogeneous blocks — e.g. ``models.transformer.transformer_lm``.

    The graph's topo order is cut by ``block_pattern`` into prologue →
    n_blocks repeated blocks → epilogue. The blocks are distributed over
    the ``axis`` mesh dimension (n_blocks divisible by the axis size; each
    stage runs ``n_blocks/S`` consecutive blocks **with the graph's own
    vertex semantics** — SelfAttentionLayer, LayerNormalization,
    TimeDistributedDense, ElementWiseVertex residuals, ...). Stage params
    live only on their stage's device (1/S memory); microbatches ride the
    shift-register schedule of :func:`make_pipeline_forward`; prologue,
    epilogue and the loss head run replicated and reuse the network's own
    ``_output_score`` math, so the loss/gradients are exactly the
    single-device ones.

    Reference bar: the reference's distributed paths serve arbitrary user
    nets (``ParallelWrapper.java:37-204``); this brings pipeline
    parallelism to the graph DSL instead of bespoke stacks.

    Constraints (validated loudly): stateless, dropout-free vertices inside
    the pipelined region; no l1/l2 regularization (the penalty would need
    the stage-stacked tree remapped); single loss output.
    """

    def __init__(self, net, mesh: Mesh, *, axis: str = "pp",
                 n_micro: Optional[int] = None,
                 batch_axis: Optional[str] = None,
                 block_pattern: str = r"^(blk\d+)_"):
        from ..optimize import updaters as _updaters

        if net.params is None:
            net.init()
        if batch_axis is not None and batch_axis not in mesh.axis_names:
            raise ValueError(f"batch_axis {batch_axis!r} not in mesh "
                             f"{mesh.axis_names}")
        self.net = net
        self.mesh = mesh
        self.axis = axis
        self.batch_axis = batch_axis
        S = int(mesh.shape[axis])
        self.S = S
        self.M = int(n_micro if n_micro is not None else S)
        conf = net.conf
        self.pre, self.blocks, self.post = _partition_pipeline(
            conf, block_pattern)
        if len(self.blocks) % S:
            raise ValueError(
                f"{len(self.blocks)} blocks not divisible by pipeline "
                f"stages {S}")
        self.k = len(self.blocks) // S
        self._validate_pipelineable()
        if len(net._output_layer_names) != 1:
            raise ValueError("pipeline training needs exactly one loss "
                             "output")

        # canonical per-block param structure: [params_of_each_vertex...]
        def block_params(names):
            return [net.params[n] for n in names]

        # stage s = blocks [s*k, (s+1)*k); stack stages on a leading axis
        per_stage = [
            [block_params(self.blocks[s * self.k + j][1])
             for j in range(self.k)]
            for s in range(S)]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *per_stage)

        def run_vertices(names, params_by_name, acts, mb):
            for n in names:
                xs = [acts[s] for s in conf.vertex_inputs[n]]
                v = conf.vertices[n]
                out, _ = v.apply(params_by_name[n], xs, state={},
                                 train=True, rng=None,
                                 masks=[None] * len(xs),
                                 policy=net.policy, minibatch=mb)
                acts[n] = out
            return acts

        blocks = self.blocks
        k = self.k

        def stage_fn(stage_params, x):
            # stage_params: [k][n_vertices_per_block] param dicts; vertex
            # semantics come from block 0's conf (stages are homogeneous)
            h = x
            for j in range(k):
                names = blocks[j][1]   # structural template
                acts = {conf.vertex_inputs[names[0]][0]: h}
                # external input name differs per block; remap: every
                # external read in the template resolves to h
                ext = {src for n in names for src in conf.vertex_inputs[n]
                       if src not in set(names)}
                for e in ext:
                    acts[e] = h
                pmap = dict(zip(names, stage_params[j]))
                acts = run_vertices(names, pmap, acts, x.shape[0])
                h = acts[names[-1]]
            return h

        fwd = make_pipeline_forward(mesh, axis, stage_fn, S, self.M,
                                    batch_axis=batch_axis)

        pro_names, post_names = self.pre, self.post
        out_name = net._output_layer_names[0]
        consumed = {i for ins in conf.vertex_inputs.values() for i in ins}

        def loss_fn(params, inputs, labels):
            pro, stages, post = params
            B = inputs[0].shape[0]
            acts = dict(zip(conf.network_inputs, inputs))
            acts = run_vertices(pro_names, pro, acts, B)
            h = acts[self.pre[-1]] if self.pre else acts[conf.network_inputs[0]]
            bm = B // self.M
            hm = h.reshape((self.M, bm) + h.shape[1:])
            ym = fwd(stages, hm)
            acts[self.blocks[-1][1][-1]] = ym.reshape((B,) + ym.shape[2:])
            total = 0.0
            for n in post_names:
                if n == out_name:
                    total = total + net._output_score(
                        post, n, acts[conf.vertex_inputs[n][0]],
                        labels[0], None, None, minibatch=B)
                if n != out_name or n in consumed:
                    acts = run_vertices([n], post, acts, B)
            return total.astype(jnp.float32)

        self._updater = _updaters.make_updater(net.training, None)
        pro_params = {n: net.params[n] for n in pro_names}
        post_params = {n: net.params[n] for n in post_names}
        repl = NamedSharding(mesh, P())
        stage_sh = jax.tree_util.tree_map(
            lambda a: NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1)))),
            stacked)
        self.params = (jax.device_put(pro_params, repl),
                       jax.tree_util.tree_map(jax.device_put, stacked,
                                              stage_sh),
                       jax.device_put(post_params, repl))
        self.opt_state = self._updater.init(self.params)
        t = net.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater

        def step(params, opt_state, inputs, labels, it):
            loss, grads = jax.value_and_grad(loss_fn)(params, inputs, labels)
            grads = _updaters.normalize_gradients(grads, norm_kind, norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, it)
            params = _updaters.apply_updates(params, deltas)
            return params, opt_state, loss

        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._fwd_loss = jax.jit(loss_fn)
        self._batch_sharding = NamedSharding(mesh, P(batch_axis))

    def _validate_pipelineable(self) -> None:
        # the WHOLE graph, not just the pipelined region: the pipeline
        # loss_fn runs every vertex with rng=None (no dropout) and never
        # adds _reg_penalty, so dropout/l1/l2 anywhere would silently
        # diverge from the single-device run — reject loudly instead
        from ..nn.conf.moe import MoELayer

        net, conf = self.net, self.net.conf
        for n in conf.topological_order():
            v = conf.vertices[n]
            if isinstance(getattr(v, "layer", None), MoELayer):
                # run_vertices drops vertex state, so the MoE aux_loss
                # (load balancing) would silently vanish from the pipeline
                # objective and diverge from the single-device loss
                raise ValueError(
                    f"vertex {n!r} is a MoELayer — its aux_loss cannot "
                    "ride the pipeline schedule yet; use "
                    "ExpertParallelGraphTrainer for MoE models")
            if v.init_state(net.policy):
                raise ValueError(
                    f"vertex {n!r} carries state (e.g. BN running stats) — "
                    "pipeline training runs all vertices stateless")
            layer = getattr(v, "layer", None)
            if layer is not None and getattr(layer, "dropout", None):
                raise ValueError(
                    f"vertex {n!r} uses dropout — not supported under "
                    "pipeline training yet")
            if layer is not None and (getattr(layer, "l1", None)
                                      or getattr(layer, "l2", None)):
                raise ValueError(
                    f"vertex {n!r} sets l1/l2 — regularization is not "
                    "supported under pipeline training yet")

    def fit_batch(self, inputs, labels) -> jax.Array:
        """One pipelined update on GLOBAL [b, ...] arrays (b divisible by
        n_micro)."""
        net = self.net
        xs, ys = self._stage_batch(inputs), self._stage_batch(labels)
        from .sequence import _reject_tbptt_chunking
        _reject_tbptt_chunking(net, xs, "GraphPipelineTrainer.fit_batch")
        it = jnp.asarray(net._update_count, jnp.int32)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, xs, ys, it)
        net._update_count += 1
        net._score = loss
        net._fire_iteration(xs[0].shape[0], loss)
        return loss

    def _stage_batch(self, arrs):
        from .sequence import _as_list
        out = [jax.device_put(jnp.asarray(a), self._batch_sharding)
               for a in _as_list(arrs)]
        if out[0].shape[0] % self.M:
            raise ValueError(f"batch {out[0].shape[0]} not divisible by "
                             f"n_micro={self.M}")
        return out

    def score_for(self, inputs, labels) -> float:
        return float(self._fwd_loss(self.params, self._stage_batch(inputs),
                                    self._stage_batch(labels)))

    def sync_to_net(self) -> None:
        """Write the trained stage params back into ``net.params`` (vertex
        name keyed, fully replicated) so the user's graph can save /
        evaluate / serve as usual."""
        pro, stages, post = self.params
        host = jax.tree_util.tree_map(lambda a: jax.device_get(a), stages)
        net = self.net
        for n, p in pro.items():
            net.params[n] = jax.device_get(p)
        for n, p in post.items():
            net.params[n] = jax.device_get(p)
        for s in range(self.S):
            stage = jax.tree_util.tree_map(lambda a: a[s], host)
            for j in range(self.k):
                _, names = self.blocks[s * self.k + j]
                for name, vparams in zip(names, stage[j]):
                    net.params[name] = vparams
