"""ParallelWrapper: data-parallel training over a device mesh.

Parity: reference ``ParallelWrapper.java:37-204`` (single-node multi-device,
parameter averaging every ``averagingFrequency`` iterations, updater-state
averaging at ``:163-186``) and ``ParameterAveragingTrainingMaster.java:763-832``
(the Spark multi-node variant of the same algorithm).

See package docstring for the two modes (sync SPMD vs local-SGD). Both
modes are SINGLE-PROCESS programs over one mesh: every replica lives in
this process, so a replica cannot "die" independently. The cross-PROCESS
analog of the local-SGD mode — where a host can be preempted mid-window
and rejoin — is :mod:`deeplearning4j_tpu.parallel.elastic`, which also
composes with this class: an ``ElasticTrainer`` built with a mesh runs
its per-host local steps through a sync-mode ``ParallelWrapper``
(``stepper_factory``), nesting in-host data parallelism under the
fleet-level bounded-staleness rounds.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng
from ..optimize import updaters as _updaters
from .mesh import data_parallel_mesh
from .stats import maybe_time_phase

Pytree = Any


def _tree_map(f, *trees):
    # treat None as a leaf so optional masks ride through untouched
    return jax.tree_util.tree_map(f, *trees, is_leaf=lambda x: x is None)


from ..util.netutil import is_graph as _is_graph


def _net_states(net):
    """states in whatever structure the net's _loss_fn expects."""
    return net._states_map() if _is_graph(net) else net._states_list()


def _batchify(net, x, y, mask):
    """Convert a batch to the form the net's _loss_fn expects: arrays for
    MultiLayerNetwork, lists of arrays for ComputationGraph (multi-in/out)."""
    def conv(v):
        if v is None:
            return None
        if isinstance(v, (list, tuple)):
            return [None if a is None else jnp.asarray(a) for a in v]
        return jnp.asarray(v)
    x, y, mask = conv(x), conv(y), conv(mask)
    if _is_graph(net):
        x = x if isinstance(x, list) else [x]
        y = y if isinstance(y, list) else [y]
        if mask is not None and not isinstance(mask, list):
            mask = [mask]
    return x, y, mask


def _batch_dim(x) -> int:
    leaf = x[0] if isinstance(x, (list, tuple)) else x
    return int(leaf.shape[0])


class ParallelWrapper:
    """Wrap an (initialized) network for data-parallel training.

    Usage (mirrors the reference's builder)::

        net = MultiLayerNetwork(conf).init()
        pw = ParallelWrapper(net, mesh=None, averaging_frequency=1)
        pw.fit(iterator, epochs=2)        # trains net in place

    ``averaging_frequency=1`` → per-step gradient all-reduce (sync SPMD).
    ``averaging_frequency=k>1`` → independent per-replica steps; params +
    updater state + layer states averaged every k iterations.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 averaging_frequency: int = 1, stats=None,
                 skip_nonfinite_budget: Optional[int] = None):
        if net.params is None:
            net.init()
        self.net = net
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        if "data" not in self.mesh.axis_names:
            raise ValueError(f"mesh must have a 'data' axis, got {self.mesh.axis_names}")
        self.averaging_frequency = int(averaging_frequency)
        self.n_devices = self.mesh.shape["data"]
        self._local: Optional[_LocalSgdState] = None
        # resilience: with a budget set, steps whose gradients (or loss)
        # are non-finite are skipped ON DEVICE (old params/opt-state kept)
        # and counted on the host, raising once the budget is exhausted.
        # The per-step finiteness read forces a host sync, so this is an
        # opt-in robustness feature, off (None) by default.
        self.nonfinite_guard = None
        if skip_nonfinite_budget is not None:
            from ..util.resilience import NonFiniteGuard
            self.nonfinite_guard = NonFiniteGuard(
                int(skip_nonfinite_budget), net)
        # phase timing (parity: SparkTrainingStats / StatsCalculationHelper);
        # stats=True builds a default collector, or pass a TrainingStats
        if stats is True:
            from .stats import TrainingStats
            stats = TrainingStats()
        self.stats = stats or None
        if self.averaging_frequency == 1:
            # install the sharded step as the net's pinned train-step
            # override: net.fit then runs SPMD transparently (the
            # override slot bypasses the trace-env cache keying)
            net._jit_cache["train_step_override"] = self._make_sync_step()
        elif self.averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")

    # ------------------------------------------------------------------
    # sync mode: one SPMD step, batch sharded, params replicated
    # ------------------------------------------------------------------

    def _make_sync_step(self):
        net = self.net
        t = net.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = net._updater
        repl = NamedSharding(self.mesh, P())
        bsh = NamedSharding(self.mesh, P("data"))

        guard = self.nonfinite_guard

        def step(params, opt_state, states, x, y, mask, rng, iteration):
            (loss, new_states), grads = jax.value_and_grad(
                net._loss_fn, has_aux=True)(params, states, x, y, mask, rng)
            if guard is not None:
                ok = jnp.logical_and(_updaters.all_finite(grads),
                                     _updaters.all_finite(loss))
            grads = _updaters.normalize_gradients(grads, norm_kind, norm_thr)
            deltas, opt_state2 = updater.update(grads, opt_state, iteration)
            params2 = _updaters.apply_updates(params, deltas)
            if guard is None:
                return params2, opt_state2, new_states, loss
            # divergent step: keep the old params/opt-state/states (a pure
            # no-op update); the host counts the skip against the budget
            params2 = _updaters.select_tree(ok, params2, params)
            opt_state2 = _updaters.select_tree(ok, opt_state2, opt_state)
            new_states = _updaters.select_tree(ok, new_states, states)
            return params2, opt_state2, new_states, loss, ok

        n_out = 5 if guard is not None else 4
        jitted = jax.jit(
            step,
            donate_argnums=(0, 1),
            in_shardings=(repl, repl, repl, bsh, bsh, bsh, repl, repl),
            out_shardings=tuple([repl] * n_out))

        n = self.n_devices

        def checked(params, opt_state, states, x, y, mask, rng, iteration):
            bs = _batch_dim(x)
            if bs % n:
                raise ValueError(
                    f"batch size {bs} not divisible by the {n}-device "
                    "'data' mesh axis (sync SPMD mode shards the batch "
                    "evenly across devices)")
            out = jitted(params, opt_state, states, x, y, mask, rng,
                         iteration)
            if guard is None:
                return out
            params, opt_state, new_states, loss, ok = out
            try:
                # the returned (selected) params are the valid tree — the
                # inputs were donated; attribution replays against them
                guard.step(ok, batch=(x, y, mask), params=params)
            except Exception:
                # the caller assigns net state only after we return, but
                # the inputs were donated — hand the (unchanged, freshly
                # selected) trees back so the net stays checkpointable
                net.params = params
                net.updater_state = opt_state
                raise
            return params, opt_state, new_states, loss

        return checked

    # ------------------------------------------------------------------
    # local-SGD mode: stacked replicas via shard_map + periodic averaging
    # ------------------------------------------------------------------

    def _ensure_local(self) -> "_LocalSgdState":
        if self._local is None:
            self._local = _LocalSgdState(self)
        return self._local

    # ------------------------------------------------------------------
    # fit API (delegates to net.fit in sync mode)
    # ------------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None) -> None:
        if self.averaging_frequency == 1 and self.stats is None:
            if _is_graph(self.net):
                if mask is not None:
                    raise ValueError(
                        "ComputationGraph: pass masks via DataSet batches, "
                        "not the mask kwarg")
                self.net.fit(data, labels, epochs=epochs)
            else:
                self.net.fit(data, labels, epochs=epochs, mask=mask)
            return
        if self.averaging_frequency == 1 and _is_graph(self.net) \
                and mask is not None:
            raise ValueError(
                "ComputationGraph: pass masks via DataSet batches, "
                "not the mask kwarg")
        local = (self._ensure_local()
                 if self.averaging_frequency > 1 else None)
        net = self.net
        from ..util import ingest as _ingest
        single = (labels is not None or hasattr(data, "shape")
                  or hasattr(data, "features"))
        for epoch in range(epochs):
            # lazy epoch-start reset (final epoch never restarts the
            # producer); revive an iterator a previous fit() exhausted
            if hasattr(data, "reset") and (
                    epoch > 0 or (hasattr(data, "has_next")
                                  and not data.has_next())):
                data.reset()
            for l in net.listeners:
                l.on_epoch_start(net, net.epoch_count)
            source = net._as_batches(data, labels, mask)
            staged = None
            if (not single and _ingest.staging_enabled()
                    and not _ingest.already_staged(data)):
                # prefetch-only staging (device_put=False): the sharded
                # replica step places batches with its own shardings, so
                # ingest here overlaps host batch PREP, not placement
                staged = _ingest.stage(source, stage_name="parallel",
                                       device_put=False)
                source = staged
            batch_iter = iter(source)
            n_batches = 0
            try:
                while True:
                    with maybe_time_phase(self.stats, "batch_prep"):
                        batch = next(batch_iter, None)
                    if batch is None:
                        break
                    n_batches += 1
                    x, y, m = batch
                    if local is not None:
                        self._timed_local_step(local, x, y, m)
                    else:
                        self._timed_sync_step(x, y, m)
            finally:
                if staged is not None:
                    staged.close()
            if n_batches == 0 and epoch > 0:
                raise ValueError(
                    f"epoch {epoch} yielded no batches — the data iterator is "
                    "exhausted and not resettable; pass arrays/DataSets or a "
                    "resettable iterator for multi-epoch fit")
            for l in net.listeners:
                l.on_epoch_end(net, net.epoch_count)
            net.epoch_count += 1
        if local is not None:
            self._timed_sync_to_net(local)

    def _timed_sync_step(self, x, y, mask):
        holder = []
        with maybe_time_phase(self.stats, "step", holder):
            loss = self.net.fit_batch(x, y, mask)
            holder.append(loss)
        return loss

    def _timed_local_step(self, local, x, y, mask):
        holder = []
        with maybe_time_phase(self.stats, "step", holder):
            loss = local.fit_batch(x, y, mask)
            holder.append(loss)
        if local._steps_since_avg == 0:
            self._timed_sync_to_net(local)
        return loss

    def _timed_sync_to_net(self, local):
        holder = []
        with maybe_time_phase(self.stats, "sync_to_net", holder):
            local.sync_to_net()
            holder.append(self.net.params)

    def fit_batch(self, x, y, mask=None) -> float:
        """One update. In local-SGD mode replicas step independently and the
        average happens only every ``averaging_frequency`` calls (matching the
        reference's semantics); the wrapped net's params are refreshed at each
        averaging point — call :meth:`finish` (or ``average_now``) after the
        last batch to flush a partial window."""
        if self.averaging_frequency == 1:
            return self._timed_sync_step(x, y, mask)
        return self._timed_local_step(self._ensure_local(), x, y, mask)

    def finish(self) -> None:
        """Flush local-SGD replicas into the wrapped net (average + sync)."""
        if self._local is not None:
            self._local.sync_to_net()

    def average_now(self) -> None:
        """Force a parameter average (local-SGD mode)."""
        if self._local is not None:
            self._local.average()
            self._local.sync_to_net()


class _LocalSgdState:
    """Per-replica parameter copies + the shard_map step (local-SGD mode)."""

    def __init__(self, pw: ParallelWrapper):
        self.pw = pw
        self.net = pw.net
        self.mesh = pw.mesh
        self.n = pw.n_devices
        self.k = pw.averaging_frequency
        self._steps_since_avg = 0
        net = self.net
        stack = lambda a: jnp.broadcast_to(a[None], (self.n,) + a.shape)
        dev_sh = NamedSharding(self.mesh, P("data"))
        self.params = jax.device_put(_tree_map(stack, net.params), dev_sh)
        self.opt_state = jax.device_put(_tree_map(stack, net.updater_state), dev_sh)
        self.states = jax.device_put(_tree_map(stack, _net_states(net)), dev_sh)
        self._step = self._make_step()
        self._avg = self._make_avg()

    def _make_step(self):
        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        net = self.net
        t = net.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = net._updater
        mesh = self.mesh

        guard = self.pw.nonfinite_guard

        def per_replica(params, opt_state, states, x, y, mask, rng, iteration):
            # leading replica axis has block size 1 on each device — drop it
            params0 = _tree_map(lambda a: a[0], params)
            opt_state0 = _tree_map(lambda a: a[0], opt_state)
            states0 = _tree_map(lambda a: a[0], states)
            # distinct dropout stream per replica
            rng = (None if rng is None
                   else jax.random.fold_in(rng, jax.lax.axis_index("data")))
            (loss, new_states), grads = jax.value_and_grad(
                net._loss_fn, has_aux=True)(params0, states0, x, y, mask, rng)
            if guard is not None:
                ok = jnp.logical_and(_updaters.all_finite(grads),
                                     _updaters.all_finite(loss))
            grads = _updaters.normalize_gradients(grads, norm_kind, norm_thr)
            deltas, opt_state1 = updater.update(grads, opt_state0, iteration)
            params1 = _updaters.apply_updates(params0, deltas)
            if guard is not None:
                # this replica diverged: its update becomes a no-op (the
                # next averaging point re-syncs it with healthy replicas)
                params1 = _updaters.select_tree(ok, params1, params0)
                opt_state1 = _updaters.select_tree(ok, opt_state1, opt_state0)
                new_states = _updaters.select_tree(ok, new_states, states0)
            put_back = lambda a: a[None] if hasattr(a, "shape") else a
            out = (_tree_map(put_back, params1),
                   _tree_map(put_back, opt_state1),
                   _tree_map(put_back, new_states), loss[None])
            if guard is not None:
                out = out + (ok[None],)
            return out

        Pd, Pr = P("data"), P()
        out_specs = (Pd, Pd, Pd, Pd) + ((Pd,) if guard is not None else ())
        step = shard_map(
            per_replica, mesh=mesh,
            in_specs=(Pd, Pd, Pd, Pd, Pd, Pd, Pr, Pr),
            out_specs=out_specs)
        return jax.jit(step, donate_argnums=(0, 1))

    def _make_avg(self):
        def avg(tree):
            return _tree_map(
                lambda a: (jnp.broadcast_to(jnp.mean(a, axis=0, keepdims=True),
                                            a.shape)
                           if hasattr(a, "shape") else a), tree)
        return jax.jit(avg, donate_argnums=(0,))

    def fit_batch(self, x, y, mask=None) -> float:
        net = self.net
        x, y, mask = _batchify(net, x, y, mask)
        bs = _batch_dim(x)
        if bs % self.n:
            raise ValueError(
                f"batch size {bs} not divisible by the {self.n}-device "
                "data axis")
        rng = _rng.fold_name(_rng.key(net.training.seed),
                             f"update_{net._update_count}")
        it = jnp.asarray(net._update_count, jnp.int32)
        out = self._step(
            self.params, self.opt_state, self.states, x, y, mask, rng, it)
        guard = self.pw.nonfinite_guard
        if guard is not None:
            self.params, self.opt_state, self.states, loss, oks = out
            n_bad = int(oks.size) - int(jnp.sum(oks))
            try:
                guard.step(n_bad == 0,
                           detail=(f"{n_bad}/{oks.size} replicas diverged; "
                                   "re-synced at next averaging"
                                   if n_bad else ""))
            except Exception:
                # budget exhausted mid-window: average the healthy
                # replicas' progress back into the net so the caller can
                # still checkpoint (mirrors the sync path's guarantee)
                self.sync_to_net()
                raise
        else:
            self.params, self.opt_state, self.states, loss = out
        net._update_count += 1
        self._steps_since_avg += 1
        if self._steps_since_avg >= self.k:
            self.average()
        score = jnp.mean(loss)  # stays on device; score() syncs lazily
        net._score = score
        net._fire_iteration(bs, score)
        return score

    def average(self) -> None:
        """Parameter + updater-state + layer-state averaging
        (parity: ``ParallelWrapper.java:145,:163-186``)."""
        holder = []
        with maybe_time_phase(self.pw.stats, "average", holder):
            self.params = self._avg(self.params)
            self.opt_state = self._avg(self.opt_state)
            self.states = self._avg(self.states)
            holder.append(self.params)
        self._steps_since_avg = 0

    def sync_to_net(self) -> None:
        """Propagate replica-0 (= averaged) values back to the wrapped net."""
        if self._steps_since_avg:
            self.average()
        take0 = lambda a: a[0] if hasattr(a, "shape") else a
        net = self.net
        net.params = _tree_map(take0, self.params)
        net.updater_state = _tree_map(take0, self.opt_state)
        states = _tree_map(take0, self.states)
        net._persist_states(states)
