"""Multi-host (multi-process) bootstrap and global meshes.

Role parity: the reference's multi-node story is Spark — a driver broadcasts
the model and workers train partitions
(``dl4j-spark/src/main/java/org/deeplearning4j/spark/impl/multilayer/
SparkDl4jMultiLayer.java:211-291``,
``.../impl/paramavg/ParameterAveragingTrainingMaster.java:340-374``), shipping
O(params) over TCP every averaging round.

TPU-native design: every host runs the SAME SPMD program; ``jax.distributed``
stitches the processes into one runtime, ``jax.devices()`` becomes the global
device list, and XLA routes collectives over ICI within a slice and DCN
across slices. There is no driver and no parameter shipping — the "cluster
orchestration layer" collapses into (1) this bootstrap, (2) a global mesh
whose outer axis maps to the process/DCN boundary, and (3) per-process data
feeding (`host_local_batch` for batch-sharded axes, `host_replicated_batch`
for tensor/pipeline-axis meshes).

Membership + round state for ELASTIC fleets (hosts that may die and
rejoin) deliberately does NOT ride on ``jax.distributed``: its
collectives hang on a dead peer, the exact failure this layer must
survive. That state lives on the coordination-store seam instead —
heartbeat leases, the append-only membership log and the round ledger in
:mod:`deeplearning4j_tpu.parallel.elastic` — and ``agree_on_digest``
takes an injectable ``allgather`` precisely so the elastic layer can run
the same commit gate over its store-backed gather.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("deeplearning4j_tpu")

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> None:
    """Join (or form) the multi-host JAX runtime.

    On Cloud TPU pods, all arguments auto-detect from the metadata server —
    call with no args on every host. Elsewhere pass the coordinator's
    ``host:port``, the world size and this process's rank (the analog of the
    reference's Spark master URL + executor registration).

    Single-process use (no coordinator, ``num_processes`` in (None, 1)) is a
    no-op so the same training script runs unchanged on one host.
    """
    global _initialized
    if _initialized:
        return
    explicit = (coordinator_address is not None
                or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if not explicit and num_processes in (None, 1):
        return  # single-process: nothing to bootstrap
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    _enable_cpu_collectives()
    jax.distributed.initialize(**kwargs)
    _initialized = True


def _enable_cpu_collectives() -> None:
    """A multi-process CPU runtime needs a cross-process collectives
    implementation — the default ("none") raises INVALID_ARGUMENT
    ("Multiprocess computations aren't implemented on the CPU backend")
    at the FIRST collective, which presents as a mysteriously failing
    worker. Select gloo before the backend initializes; harmless for
    TPU/GPU runtimes (the knob only affects CPU client creation)."""
    try:
        from jax._src import xla_bridge as _xb
        cur = _xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except Exception:
        return                      # older/newer jax: nothing to do
    if cur != "none":
        return
    try:
        from jax._src.lib import xla_client
        if not hasattr(xla_client._xla, "make_gloo_tcp_collectives"):
            return                  # jaxlib built without gloo
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:               # never block a TPU pod bootstrap
        logger.warning("could not enable gloo CPU collectives",
                       exc_info=True)


def is_initialized() -> bool:
    return _initialized


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_mesh(axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh over ALL devices in the (possibly multi-host) runtime.

    Default: 1-D ``data`` mesh over every global device. With ``axes``, the
    product must equal the global device count; devices are arranged so the
    FIRST axis varies slowest across processes — shard the first axis by
    host-boundary-tolerant traffic (data parallelism) and inner axes by
    ICI-hungry traffic (tensor/sequence parallelism), scaling-book style.
    """
    devs = jax.devices()
    if axes is None:
        return Mesh(np.asarray(devs), ("data",))
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    total = int(np.prod(shape))
    if total != len(devs):
        raise ValueError(
            f"mesh {axes} needs {total} devices, runtime has {len(devs)} "
            f"across {jax.process_count()} process(es)")
    n_proc = jax.process_count()
    try:
        from jax.experimental import mesh_utils
        if n_proc > 1 and shape[0] % n_proc == 0:
            # DCN (process) boundary rides the first axis, ICI inside
            arr = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(shape[0] // n_proc,) + shape[1:],
                dcn_mesh_shape=(n_proc,) + (1,) * (len(shape) - 1),
                devices=devs).reshape(shape)
        else:
            arr = mesh_utils.create_device_mesh(shape, devices=devs)
    except Exception:
        arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, names)


def agree_on_digest(digest: str, *, allgather=None) -> bool:
    """Pre-commit barrier for the multi-process dp path: every host
    presents its training-state digest (``util.durable.params_digest``)
    and the checkpoint commits only if ALL hosts agree — a diverged
    replica (bad host, dropped collective) must not publish its state as
    THE recovery point.

    ``allgather`` is injectable for tests; the default uses
    ``multihost_utils.process_allgather`` (single-process: trivially
    True).
    """
    local = np.frombuffer(bytes.fromhex(digest), dtype=np.uint8)
    if allgather is None:
        if jax.process_count() == 1:
            return True
        from jax.experimental import multihost_utils
        allgather = multihost_utils.process_allgather
    world = np.atleast_2d(np.asarray(allgather(local)))
    return bool((world == world[0]).all())


def host_replicated_batch(mesh: Mesh, *arrays):
    """Assemble REPLICATED global device arrays from identical per-process
    host arrays — the feeding path for meshes whose axes carry model
    state rather than batch shards (tensor/pipeline-axis meshes crossing
    the process boundary, VERDICT item 7). Every process must pass the
    same full array; the result is replicated over the whole mesh so a
    tensor-parallel step can consume it regardless of which axis spans
    DCN. Single-process: plain ``device_put`` with a replicated sharding.
    """
    sharding = NamedSharding(mesh, P())
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        a = np.asarray(a)
        if jax.process_count() == 1:
            out.append(jax.device_put(a, sharding))
        else:
            out.append(jax.make_array_from_process_local_data(
                sharding, a, a.shape))
    return out[0] if len(out) == 1 else tuple(out)


def host_local_batch(mesh: Mesh, *arrays, axis: str = "data"):
    """Assemble global device arrays from per-process host-local batches.

    Each process passes ITS shard of the global batch (the analog of a Spark
    worker reading its RDD partition); the result is a global array sharded
    over ``axis`` that the jitted SPMD step consumes directly. Single-process:
    equivalent to ``jax.device_put`` with the batch sharding.
    """
    sharding = NamedSharding(mesh, P(axis))
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        a = np.asarray(a)
        if jax.process_count() == 1:
            out.append(jax.device_put(a, sharding))
        else:
            global_shape = (a.shape[0] * jax.process_count(),) + a.shape[1:]
            out.append(jax.make_array_from_process_local_data(
                sharding, a, global_shape))
    return out[0] if len(out) == 1 else tuple(out)
