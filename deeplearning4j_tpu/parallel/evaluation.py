"""Mesh-sharded evaluation and scoring.

Parity: reference distributed evaluation — workers evaluate partitions and
the driver reduces the ``Evaluation`` objects
(``dl4j-spark/src/main/java/org/deeplearning4j/spark/impl/multilayer/
evaluation/EvaluateFlatMapFunction.java``, ``EvaluationReduceFunction.java``)
plus distributed scoring (``scoring/ScoreExamplesFunction.java``).

TPU-native design: ONE jitted forward with the batch sharded over the
``data`` mesh axis — XLA splits the work across devices, no executor
round-trips. Indivisible batches are padded and the padding masked out of the
metrics, so any iterator works unchanged. The host-side ``Evaluation``
accumulation IS the reduce (its ``merge()`` remains for cross-process use:
each process evaluates its shard, then merges).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import data_parallel_mesh

Pytree = Any


from ..util.netutil import is_graph as _is_graph


def _pad_to(x: np.ndarray, m: int):
    b = x.shape[0]
    pad = (-b) % m
    if pad == 0:
        return x, b
    reps = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
    return reps, b


class ShardedEvaluator:
    """Evaluate / score a network with batches sharded over the mesh.

    Usage::

        ev = ShardedEvaluator(net, mesh).evaluate(test_iterator)
        print(ev.stats())
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 axis: str = "data"):
        if net.params is None:
            net.init()
        if _is_graph(net) and (len(net.conf.network_inputs) != 1
                               or len(net.conf.network_outputs) != 1):
            raise ValueError(
                "ShardedEvaluator supports single-input/single-output "
                f"graphs; got {len(net.conf.network_inputs)} inputs / "
                f"{len(net.conf.network_outputs)} outputs — evaluate "
                "multi-io graphs per-output with net.output() + Evaluation")
        self.net = net
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        if axis not in self.mesh.axis_names:
            raise ValueError(
                f"mesh has no {axis!r} axis: {self.mesh.axis_names}")
        self.axis = axis
        self.n = self.mesh.shape[axis]
        self._fwd = None

    def _forward(self):
        if self._fwd is not None:
            return self._fwd
        net = self.net
        repl = NamedSharding(self.mesh, P())
        bsh = NamedSharding(self.mesh, P(self.axis))

        if _is_graph(net):
            def fwd(params, states, x):
                acts, _ = net._forward(params, states, [x], train=False)
                return acts[net.conf.network_outputs[0]]
        else:
            def fwd(params, states, x):
                out, _ = net._forward(params, states, x, train=False)
                return out

        self._fwd = jax.jit(fwd, in_shardings=(repl, repl, bsh),
                            out_shardings=bsh)
        return self._fwd

    def _states(self):
        net = self.net
        return net._states_map() if _is_graph(net) else net._states_list()

    def output(self, x) -> np.ndarray:
        """Sharded forward on one (possibly indivisible) batch."""
        x = np.asarray(x)
        xp, b = _pad_to(x, self.n)
        out = self._forward()(self.net.params, self._states(), jnp.asarray(xp))
        return np.asarray(out)[:b]

    def evaluate(self, data, labels=None, evaluation=None):
        """Sharded ``Evaluation`` over an iterator / arrays. Pass an existing
        ``evaluation`` to accumulate across processes, then ``merge()``."""
        from ..eval import Evaluation
        ev = evaluation if evaluation is not None else Evaluation()
        for x, y, m in self.net._as_batches(data, labels):
            out = self.output(np.asarray(x))
            ev.eval(np.asarray(y), out,
                    mask=None if m is None else np.asarray(m))
        if hasattr(data, "reset"):
            data.reset()
        return ev

    def _loss(self):
        if getattr(self, "_loss_fn", None) is not None:
            return self._loss_fn
        net = self.net
        repl = NamedSharding(self.mesh, P())
        bsh = NamedSharding(self.mesh, P(self.axis))

        if _is_graph(net):
            def loss(params, states, x, y):
                l, _ = net._loss_fn(params, states, [x], [y], None, None)
                return l
        else:
            def loss(params, states, x, y):
                l, _ = net._loss_fn(params, states, x, y, None, None)
                return l

        self._loss_fn = jax.jit(loss, in_shardings=(repl, repl, bsh, bsh),
                                out_shardings=repl)
        return self._loss_fn

    def score(self, data, labels=None, average: bool = True) -> float:
        """Sharded mean loss (parity: distributed ``calculateScore``);
        batches not divisible by the mesh axis fall back to the unsharded
        scorer so padding never pollutes the mean."""
        net = self.net
        total, n = 0.0, 0
        for x, y, m in net._as_batches(data, labels):
            x, y = np.asarray(x), np.asarray(y)
            b = x.shape[0]
            if m is None and b % self.n == 0:
                s = float(self._loss()(net.params, self._states(),
                                       jnp.asarray(x), jnp.asarray(y)))
            elif _is_graph(net):
                s = net.score_for([x], [y],
                                  None if m is None else [np.asarray(m)])
            else:
                s = net.score_for(x, y, m)
            total += float(s) * b
            n += b
        if hasattr(data, "reset"):
            data.reset()
        return total / max(n, 1) if average else total


def evaluate_sharded(net, data, labels=None, mesh: Optional[Mesh] = None):
    """One-shot helper: ``evaluate_sharded(net, test_iter, mesh=mesh)``."""
    return ShardedEvaluator(net, mesh).evaluate(data, labels)
