"""Distributed-training phase timing: per-phase events, summaries, HTML
timeline export.

Parity: reference Spark training stats — ``CommonSparkTrainingStats.java`` /
``StatsCalculationHelper`` (phase timers around split/repartition/fit/
aggregate) and ``StatsUtils.java:69-92`` ``exportStatsAsHtml`` (timeline
chart per phase). Here the phases are the TPU pipeline's: host batch prep,
sharded step dispatch, replica averaging, net sync, epoch boundaries.

Honesty note on async dispatch: XLA returns control before the device
finishes, so a ``step`` phase measures dispatch unless ``blocking=True``
(which inserts a ``block_until_ready`` barrier — accurate per-step wall time
at some throughput cost; the reference has no such distinction because ND4J
ops were synchronous).
"""

from __future__ import annotations

import dataclasses
import json
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class PhaseEvent:
    """One timed occurrence of a phase (parity: ``BaseEventStats``)."""

    phase: str
    start_ms: float
    duration_ms: float


class TrainingStats:
    """Collects phase events during distributed training.

    ``blocking=True`` waits for device results inside timed sections so step
    durations are true device times, not dispatch times.
    """

    def __init__(self, blocking: bool = False, registry=None):
        self.blocking = blocking
        self.events: List[PhaseEvent] = []
        self._origin = time.perf_counter()
        # optional mirror into the metrics plane: each phase event also
        # lands in training_phase_seconds{phase=...} so distributed phase
        # timings ride the same scrape as serving/resilience metrics
        self._phase_hist = None
        if registry is not None:
            self._phase_hist = registry.histogram(
                "training_phase_seconds",
                "Distributed-training phase durations", ("phase",),
                buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                         0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))

    def _now_ms(self) -> float:
        return (time.perf_counter() - self._origin) * 1000.0

    def _add(self, event: PhaseEvent) -> None:
        self.events.append(event)
        if self._phase_hist is not None:
            self._phase_hist.observe(event.duration_ms / 1000.0,
                                     phase=event.phase)

    @contextmanager
    def time_phase(self, phase: str, result_holder: Optional[list] = None):
        """Context manager timing one phase occurrence. If ``blocking`` and
        ``result_holder`` ends up holding device values, waits on them before
        closing the measurement."""
        t0 = self._now_ms()
        try:
            yield
        finally:
            if self.blocking and result_holder:
                import jax
                for leaf in jax.tree_util.tree_leaves(result_holder):
                    if hasattr(leaf, "block_until_ready"):
                        leaf.block_until_ready()
            self._add(PhaseEvent(phase, t0, self._now_ms() - t0))

    def record(self, phase: str, start_ms: float, duration_ms: float) -> None:
        self._add(PhaseEvent(phase, start_ms, duration_ms))

    # ------------------------------------------------------------------
    # summaries (parity: CommonSparkTrainingStats getValue/statsAsString)
    # ------------------------------------------------------------------

    def phases(self) -> List[str]:
        seen: Dict[str, None] = {}
        for e in self.events:
            seen.setdefault(e.phase, None)
        return list(seen)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for p in self.phases():
            ds = [e.duration_ms for e in self.events if e.phase == p]
            out[p] = {
                "count": len(ds),
                "total_ms": round(sum(ds), 3),
                "mean_ms": round(sum(ds) / len(ds), 3),
                "min_ms": round(min(ds), 3),
                "max_ms": round(max(ds), 3),
            }
        return out

    def as_json(self) -> str:
        return json.dumps({
            "summary": self.summary(),
            "events": [dataclasses.asdict(e) for e in self.events],
        })

    # ------------------------------------------------------------------
    # HTML timeline (parity: StatsUtils.exportStatsAsHtml :69-92, built on
    # the ui-components DSL exactly as the reference's Spark stats were)
    # ------------------------------------------------------------------

    def as_components(self) -> list:
        """Timeline + summary table as UI components."""
        from ..ui.components import ChartTimeline, ComponentTable
        timeline = ChartTimeline("Phase timeline")
        for p in self.phases():
            timeline.add_lane(p, [
                (e.start_ms, e.start_ms + e.duration_ms,
                 f"{p}: {e.duration_ms:.2f} ms @ {e.start_ms:.1f} ms")
                for e in self.events if e.phase == p])
        table = ComponentTable(
            ["phase", "count", "total ms", "mean ms", "min ms", "max ms"],
            [[p, s["count"], s["total_ms"], s["mean_ms"], s["min_ms"],
              s["max_ms"]] for p, s in self.summary().items()],
            title="Per-phase summary")
        return [timeline, table]

    def export_html(self, path: str, title: str = "Training phase timeline"
                    ) -> None:
        """Standalone HTML: one swimlane per phase, a rect per event."""
        from ..ui.components import StaticPageUtil
        StaticPageUtil.save_html(self.as_components(), path, title)


@contextmanager
def maybe_time_phase(stats: Optional[TrainingStats], phase: str,
                     result_holder: Optional[list] = None):
    """Null-safe ``time_phase``: a no-op when stats collection is off, so
    call sites need only one copy of the timed body."""
    if stats is None:
        yield
    else:
        with stats.time_phase(phase, result_holder):
            yield
