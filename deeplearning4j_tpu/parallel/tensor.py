"""Tensor parallelism: parameter sharding over the ``model`` mesh axis.

No reference analog (SURVEY §2.9: TP = NO) — north-star extension. Design:
annotate parameter shardings (column-parallel weights) and let XLA GSPMD
partition every matmul and insert the collectives; combinable with the
``data`` axis for 2-D (dp × tp) meshes. This is the standard JAX/TPU recipe
(scaling-book style): pick a mesh, shard the params, jit, let the compiler
do the rest.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng
from ..optimize import updaters as _updaters
from .dsl_trainer import ShardedDSLTrainerBase

Pytree = Any


def param_partition_specs(net, model_axis: str = "model",
                          mesh: Optional[Mesh] = None) -> Dict:
    """PartitionSpec pytree for net.params: big weights column-parallel
    (output dim sharded), biases sharded on their only dim, small/stat
    params replicated. Dims not divisible by the mesh axis stay replicated."""
    specs: Dict[str, Dict[str, P]] = {}
    ma = model_axis
    axis_size = mesh.shape[model_axis] if mesh is not None else 1

    def _ok(dim: int) -> bool:
        return dim % axis_size == 0

    def spec_for(name: str, shape) -> P:
        if len(shape) == 2 and _ok(shape[1]):   # dense/lstm kernels [in, out*]
            return P(None, ma)
        if len(shape) == 4 and _ok(shape[3]):   # conv HWIO → output channels
            return P(None, None, None, ma)
        if len(shape) == 1 and shape[0] > 1 and _ok(shape[0]):
            return P(ma)             # biases / per-channel params
        return P()

    params = net.params
    if params is None:
        raise ValueError("net.init() first")
    for key, layer_params in params.items():
        specs[key] = {name: spec_for(name, p.shape)
                      for name, p in layer_params.items()}
    return specs


def _shardings(tree_specs, mesh) -> Pytree:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree_specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_params(net, mesh: Mesh, model_axis: str = "model") -> None:
    """Place net.params (and updater state) according to the TP specs."""
    specs = param_partition_specs(net, model_axis, mesh)
    sh = _shardings(specs, mesh)
    net.params = jax.device_put(net.params, sh)
    if net.updater_state:
        # updater state is {slot_name: params-like tree} (see updaters.py
        # init functions), so each slot takes the param shardings structurally
        placed = {}
        for slot_name, slot in net.updater_state.items():
            try:
                placed[slot_name] = jax.device_put(slot, sh)
            except ValueError:
                # slot does not mirror the param tree: replicate it
                placed[slot_name] = jax.device_put(
                    slot, NamedSharding(mesh, P()))
        net.updater_state = placed


class TensorParallelTrainer:
    """2-D (data × model) sharded training for a MultiLayerNetwork.

    Usage::

        mesh = create_mesh({"data": 2, "model": 4})
        tp = TensorParallelTrainer(net, mesh)
        tp.fit_batch(x, y)          # params stay sharded across steps
    """

    def __init__(self, net, mesh: Mesh, data_axis: str = "data",
                 model_axis: str = "model"):
        if net.params is None:
            net.init()
        self.net = net
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.model_axis = model_axis
        shard_params(net, mesh, model_axis)
        self._step = self._make_step()

    def _make_step(self):
        net = self.net
        t = net.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = net._updater
        specs = param_partition_specs(net, self.model_axis, self.mesh)
        param_sh = _shardings(specs, self.mesh)
        repl = NamedSharding(self.mesh, P())
        batch_sh = NamedSharding(
            self.mesh,
            P(self.data_axis) if self.data_axis else P())

        def step(params, opt_state, states, x, y, mask, rng, iteration):
            (loss, new_states), grads = jax.value_and_grad(
                net._loss_fn, has_aux=True)(params, states, x, y, mask, rng)
            grads = _updaters.normalize_gradients(grads, norm_kind, norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, iteration)
            params = _updaters.apply_updates(params, deltas)
            return params, opt_state, new_states, loss

        # opt_state is DONATED, so its output sharding must equal its
        # input sharding exactly — pin both to the placement shard_params
        # chose (leaving it unconstrained lets GSPMD shard the output of
        # a replicated-in slot, and the aliased buffers then differ in
        # size: runtime INTERNAL error on the 2-D mesh)
        opt_sh = jax.tree_util.tree_map(lambda a: a.sharding,
                                        net.updater_state)
        return jax.jit(
            step, donate_argnums=(0, 1),
            in_shardings=(param_sh, opt_sh, repl, batch_sh, batch_sh,
                          batch_sh, repl, repl),
            out_shardings=(param_sh, opt_sh, repl, repl))

    def fit_batch(self, x, y, mask=None) -> float:
        net = self.net
        x, y = jnp.asarray(x), jnp.asarray(y)
        if mask is not None:
            mask = jnp.asarray(mask)
        rng = _rng.fold_name(_rng.key(net.training.seed),
                             f"update_{net._update_count}")
        it = jnp.asarray(net._update_count, jnp.int32)
        params, opt_state, new_states, loss = self._step(
            net.params, net.updater_state, net._states_list(), x, y, mask,
            rng, it)
        net.params = params
        net.updater_state = opt_state
        net._update_count += 1
        net._persist_states(new_states)
        net._score = loss
        net._fire_iteration(x.shape[0], loss)
        return loss


class TensorParallelGraphTrainer(ShardedDSLTrainerBase):
    """Tensor-parallel training for DSL models (``ComputationGraph`` or
    ``MultiLayerNetwork``): big weights column-parallel over
    ``model_axis`` via :func:`param_partition_specs`, batch over
    ``data_axis`` when present — GSPMD partitions every matmul and
    inserts the collectives. Shares the sharded-trainer contract (masks,
    TBPTT chunk rejection, ``output()``) with the sequence/expert
    trainers via ``ShardedDSLTrainerBase``; the original
    ``TensorParallelTrainer`` remains the MLN-tuned fast path.
    """

    _api = "TensorParallelGraphTrainer"

    def __init__(self, net, mesh: Mesh, *, data_axis: str = "data",
                 model_axis: str = "model",
                 skip_nonfinite_budget: Optional[int] = None):
        if net.params is None:
            net.init()
        if model_axis not in mesh.axis_names:
            raise ValueError(f"model_axis {model_axis!r} not in mesh "
                             f"{mesh.axis_names}")
        self.model_axis = model_axis
        batch_axis = data_axis if data_axis in mesh.axis_names else None
        specs = param_partition_specs(net, model_axis, mesh)
        shardings = _shardings(specs, mesh)
        self._build(net, mesh, x_spec=P(batch_axis), mask_spec=P(batch_axis),
                    batch_axis=batch_axis, param_shardings=shardings,
                    skip_nonfinite_budget=skip_nonfinite_budget)
