"""Updaters: per-param learning rules + LR schedules + gradient normalization.

Parity: reference ``nn/updater/LayerUpdater.java`` —
  - updater dispatch SGD/ADAM/ADADELTA/NESTEROVS/ADAGRAD/RMSPROP/NONE
    (``:242-266``, delegating to ND4J GradientUpdater impls),
  - LR schedules Exponential/Inverse/Step/TorchStep/Poly/Sigmoid/Schedule
    (``:132-155``),
  - gradient normalization RenormalizeL2PerLayer/PerParamType,
    ClipElementWiseAbsoluteValue, ClipL2PerLayer/PerParamType (``:179-226``).

TPU-native design: one updater for the whole network (pytree-wide `tree_map`
instead of per-layer GradientUpdater objects); per-layer and per-bias learning
rates become a static *LR-multiplier pytree* baked in at network build time
(the analog of `conf.getLearningRateByParam(param)` in `LayerUpdater.java`).
All of it is jit-compatible: `iteration` is a traced scalar so LR schedules
compile into the train step instead of triggering recompiles per iteration.

The convention throughout: ``update()`` returns *deltas to subtract*,
i.e. ``new_params = params - deltas`` (`apply_updates`). This matches the
reference where the updater rewrites the gradient view in place and
`StochasticGradientDescent.java:57` then does `params -= gradient`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn.conf.training import TrainingConfig

Pytree = Any


# --------------------------------------------------------------------------
# LR schedules (parity: LayerUpdater.java:132-155 LearningRatePolicy)
# --------------------------------------------------------------------------


def learning_rate_at(t: TrainingConfig, iteration) -> jax.Array:
    """Scheduled LR at `iteration` (traced-scalar friendly).

    Policies (reference enum LearningRatePolicy):
      none        lr
      exponential lr * decay^iter
      inverse     lr / (1 + decay*iter)^power
      step        lr * decay^floor(iter / steps)
      torch_step  lr * decay^floor(iter / steps)   (reference TorchStep applies
                  the decay every `steps` iterations, same closed form)
      poly        lr * (1 - iter/maxIter)^power    (maxIter := steps)
      sigmoid     lr / (1 + exp(-decay * (iter - steps)))
      schedule    piecewise-constant map {iteration: lr}
    """
    lr = jnp.asarray(t.learning_rate, jnp.float32)
    it = jnp.asarray(iteration, jnp.float32)
    policy = (t.lr_policy or "none").lower()
    if policy == "none":
        return lr
    if policy == "exponential":
        return lr * jnp.power(t.lr_policy_decay_rate, it)
    if policy == "inverse":
        return lr / jnp.power(1.0 + t.lr_policy_decay_rate * it,
                              t.lr_policy_power)
    if policy in ("step", "torch_step"):
        steps = max(float(t.lr_policy_steps), 1.0)
        return lr * jnp.power(t.lr_policy_decay_rate, jnp.floor(it / steps))
    if policy == "poly":
        max_iter = max(float(t.lr_policy_steps), 1.0)
        frac = jnp.clip(it / max_iter, 0.0, 1.0)
        return lr * jnp.power(1.0 - frac, t.lr_policy_power)
    if policy == "sigmoid":
        return lr / (1.0 + jnp.exp(-t.lr_policy_decay_rate
                                   * (it - t.lr_policy_steps)))
    if policy == "schedule":
        sched = t.lr_schedule or {}
        # piecewise-constant: start at base lr, switch at each scheduled step
        out = lr
        for step in sorted(sched):
            out = jnp.where(it >= step, jnp.float32(sched[step]), out)
        return out
    raise ValueError(f"unknown lr policy {t.lr_policy!r}")


# --------------------------------------------------------------------------
# gradient normalization (parity: LayerUpdater.java:179-226)
# --------------------------------------------------------------------------


def _global_norm(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def normalize_gradients(grads: Pytree, kind: Optional[str],
                        threshold: float = 1.0) -> Pytree:
    """Apply one of the reference's 5 GradientNormalization modes.

    The reference normalizes per *layer* (each LayerUpdater sees only its
    layer's gradient views). Here grads is the whole-network pytree of
    per-layer dicts, so "per layer" = per top-level entry and
    "per param type" = per leaf.
    """
    if not kind or kind == "none":
        return grads
    kind = kind.lower()

    if kind == "renormalize_l2_per_layer":
        def per_layer(layer_grads):
            n = _global_norm(layer_grads)
            return jax.tree_util.tree_map(
                lambda g: g / jnp.maximum(n, 1e-8).astype(g.dtype), layer_grads)
        return {k: per_layer(v) for k, v in grads.items()}

    if kind == "renormalize_l2_per_param_type":
        return jax.tree_util.tree_map(
            lambda g: g / jnp.maximum(jnp.linalg.norm(
                g.astype(jnp.float32).ravel()), 1e-8).astype(g.dtype), grads)

    if kind == "clip_elementwise_absolute_value":
        thr = jnp.float32(threshold)
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -thr, thr).astype(g.dtype), grads)

    if kind == "clip_l2_per_layer":
        def per_layer(layer_grads):
            n = _global_norm(layer_grads)
            scale = jnp.where(n > threshold, threshold / jnp.maximum(n, 1e-8), 1.0)
            return jax.tree_util.tree_map(
                lambda g: (g * scale).astype(g.dtype), layer_grads)
        return {k: per_layer(v) for k, v in grads.items()}

    if kind == "clip_l2_per_param_type":
        def per_leaf(g):
            n = jnp.linalg.norm(g.astype(jnp.float32).ravel())
            scale = jnp.where(n > threshold, threshold / jnp.maximum(n, 1e-8), 1.0)
            return (g * scale).astype(g.dtype)
        return jax.tree_util.tree_map(per_leaf, grads)

    raise ValueError(f"unknown gradient normalization {kind!r}")


# --------------------------------------------------------------------------
# updaters
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Updater:
    """A pytree-wide learning rule.

    init(params)                              -> opt state pytree
    update(grads, state, iteration)           -> (deltas, new state)
    new_params = apply_updates(params, deltas) = params - deltas
    """

    name: str
    init: Callable[[Pytree], Pytree]
    update: Callable[[Pytree, Pytree, Any], Tuple[Pytree, Pytree]]


def apply_updates(params: Pytree, deltas: Pytree) -> Pytree:
    return jax.tree_util.tree_map(lambda p, d: p - d.astype(p.dtype),
                                  params, deltas)


def all_finite(tree: Pytree) -> jax.Array:
    """Scalar bool: every inexact leaf of ``tree`` is finite (trainers use
    this on the gradient tree to skip divergent steps on-device)."""
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def select_tree(ok: jax.Array, new: Pytree, old: Pytree) -> Pytree:
    """Leaf-wise ``where(ok, new, old)`` that tolerates ``new`` growing
    container entries ``old`` lacks (layer state dicts legitimately gain
    keys at runtime, e.g. MoE aux_loss) — unmatched entries keep ``new``."""
    if isinstance(new, dict):
        old = old if isinstance(old, dict) else {}
        return {k: select_tree(ok, v, old.get(k)) for k, v in new.items()}
    if isinstance(new, (list, tuple)):
        old = old if isinstance(old, (list, tuple)) else ()
        seq = [select_tree(ok, v, old[i] if i < len(old) else None)
               for i, v in enumerate(new)]
        if isinstance(new, tuple):
            return type(new)(*seq) if hasattr(new, "_fields") else tuple(seq)
        return seq
    if new is None or old is None or not hasattr(new, "dtype"):
        return new
    return jnp.where(ok, new, old)


def _zeros_like_f32(params):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


def make_updater(t: TrainingConfig,
                 lr_multipliers: Optional[Pytree] = None) -> Updater:
    """Build the network-wide updater from a TrainingConfig.

    `lr_multipliers` is a pytree matching params whose leaves scale the
    scheduled global LR per param — this is how per-layer `learning_rate`
    and `bias_learning_rate` overrides (reference
    `conf.getLearningRateByParam`) reach the update rule. None = all 1.0.
    """
    name = (t.updater or "sgd").lower()
    eps = float(t.epsilon)

    def lr_tree(params_like, iteration):
        lr = learning_rate_at(t, iteration)
        if lr_multipliers is None:
            return jax.tree_util.tree_map(lambda _: lr, params_like)
        return jax.tree_util.tree_map(
            lambda m: lr * jnp.float32(m), lr_multipliers)

    def to_f32(g):
        return g.astype(jnp.float32)

    if name in ("sgd", "none"):
        scale = 1.0 if name == "sgd" else 0.0

        def init(params):
            return {}

        def update(grads, state, iteration):
            lrs = lr_tree(grads, iteration)
            deltas = jax.tree_util.tree_map(
                lambda g, lr: scale * lr * to_f32(g), grads, lrs)
            return deltas, state

        return Updater(name, init, update)

    if name == "nesterovs":
        mu = float(t.momentum)

        def init(params):
            return {"v": _zeros_like_f32(params)}

        def update(grads, state, iteration):
            lrs = lr_tree(grads, iteration)
            # Sutskever-style NAG (the formulation ND4J's Nesterovs updater
            # implements): v' = mu*v - lr*g ; delta = -(mu*v' - lr*g)
            v_new = jax.tree_util.tree_map(
                lambda v, g, lr: mu * v - lr * to_f32(g),
                state["v"], grads, lrs)
            deltas = jax.tree_util.tree_map(
                lambda v, g, lr: -(mu * v - lr * to_f32(g)),
                v_new, grads, lrs)
            return deltas, {"v": v_new}

        return Updater(name, init, update)

    if name == "adagrad":
        def init(params):
            return {"accum": _zeros_like_f32(params)}

        def update(grads, state, iteration):
            lrs = lr_tree(grads, iteration)
            accum = jax.tree_util.tree_map(
                lambda a, g: a + jnp.square(to_f32(g)), state["accum"], grads)
            deltas = jax.tree_util.tree_map(
                lambda a, g, lr: lr * to_f32(g) / (jnp.sqrt(a) + eps),
                accum, grads, lrs)
            return deltas, {"accum": accum}

        return Updater(name, init, update)

    if name == "rmsprop":
        decay = float(t.rms_decay)

        def init(params):
            return {"accum": _zeros_like_f32(params)}

        def update(grads, state, iteration):
            lrs = lr_tree(grads, iteration)
            accum = jax.tree_util.tree_map(
                lambda a, g: decay * a + (1 - decay) * jnp.square(to_f32(g)),
                state["accum"], grads)
            deltas = jax.tree_util.tree_map(
                lambda a, g, lr: lr * to_f32(g) / jnp.sqrt(a + eps),
                accum, grads, lrs)
            return deltas, {"accum": accum}

        return Updater(name, init, update)

    if name == "adadelta":
        rho = float(t.rho)

        def init(params):
            return {"msg": _zeros_like_f32(params),
                    "msdx": _zeros_like_f32(params)}

        def update(grads, state, iteration):
            msg = jax.tree_util.tree_map(
                lambda a, g: rho * a + (1 - rho) * jnp.square(to_f32(g)),
                state["msg"], grads)
            deltas = jax.tree_util.tree_map(
                lambda a, dx, g: jnp.sqrt(dx + eps) / jnp.sqrt(a + eps)
                * to_f32(g),
                msg, state["msdx"], grads)
            msdx = jax.tree_util.tree_map(
                lambda dx, d: rho * dx + (1 - rho) * jnp.square(d),
                state["msdx"], deltas)
            return deltas, {"msg": msg, "msdx": msdx}

        return Updater(name, init, update)

    if name in ("adam", "adamax", "nadam"):
        b1, b2 = float(t.adam_beta1), float(t.adam_beta2)

        def init(params):
            return {"m": _zeros_like_f32(params),
                    "v": _zeros_like_f32(params)}

        def update(grads, state, iteration):
            lrs = lr_tree(grads, iteration)
            tstep = jnp.asarray(iteration, jnp.float32) + 1.0
            m = jax.tree_util.tree_map(
                lambda m_, g: b1 * m_ + (1 - b1) * to_f32(g),
                state["m"], grads)
            bc1 = 1.0 - jnp.power(b1, tstep)
            if name == "adamax":
                v = jax.tree_util.tree_map(
                    lambda v_, g: jnp.maximum(b2 * v_, jnp.abs(to_f32(g))),
                    state["v"], grads)
                deltas = jax.tree_util.tree_map(
                    lambda m_, v_, lr: lr * (m_ / bc1) / (v_ + eps), m, v, lrs)
            else:
                v = jax.tree_util.tree_map(
                    lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(to_f32(g)),
                    state["v"], grads)
                bc2 = 1.0 - jnp.power(b2, tstep)
                if name == "nadam":
                    deltas = jax.tree_util.tree_map(
                        lambda m_, v_, g, lr: lr
                        * (b1 * m_ / bc1 + (1 - b1) * to_f32(g) / bc1)
                        / (jnp.sqrt(v_ / bc2) + eps),
                        m, v, grads, lrs)
                else:
                    deltas = jax.tree_util.tree_map(
                        lambda m_, v_, lr: lr * (m_ / bc1)
                        / (jnp.sqrt(v_ / bc2) + eps),
                        m, v, lrs)
            return deltas, {"m": m, "v": v}

        return Updater(name, init, update)

    raise ValueError(f"unknown updater {name!r}; known: sgd, nesterovs, "
                     "adagrad, rmsprop, adadelta, adam, adamax, nadam, none")
