"""Training listeners: per-iteration/epoch hooks fired by the fit loop.

Parity: reference ``optimize/api/IterationListener.java`` /
``TrainingListener.java`` (onEpochStart/End, onForwardPass,
onGradientCalculation, onBackwardPass — fired at
``MultiLayerNetwork.java:1046-1104``) and the impls in
``optimize/listeners/``: ``ScoreIterationListener.java``,
``PerformanceListener.java:71-86`` (samples/sec, batches/sec),
``CollectScoresIterationListener.java``, ``ComposableIterationListener.java``.

TPU-native note: the jitted train step runs async on device; listeners fire on
the host *after* the step is dispatched. ``score`` arrives as a
:class:`~deeplearning4j_tpu.util.ingest.LazyScore`: calling ``float(score)``
performs the device→host sync (counted in ``training_host_syncs_total``), so
a listener gating on ``iteration % frequency`` costs one sync per window and
a listener that never reads the score costs none. Don't read the score
outside your frequency window — that re-serializes the async dispatch loop.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Hook bus contract. `model` is the network; `iteration` is the global
    iteration counter (minibatches seen)."""

    def iteration_done(self, model, iteration: int, score) -> None:
        """``score`` is host-lazy (``LazyScore``): ``float(score)`` blocks
        on the device and transfers — do it at most once per frequency
        window."""
        pass

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass

    def on_forward_pass(self, model, activations) -> None:
        pass

    def on_gradient_calculation(self, model) -> None:
        pass

    def on_backward_pass(self, model) -> None:
        pass

    def on_step_skipped(self, model, iteration: int, reason: str,
                        info: Optional[dict] = None) -> None:
        """A training step was detected as divergent (e.g. non-finite
        gradients) and skipped — the params did not move this iteration.
        Fired by the resilience-guarded trainers (parallel wrapper /
        sharded DSL trainers with ``skip_nonfinite_budget`` set).

        ``info`` (when present) carries structured context: ``model``
        (name), ``iteration``, and — when NaN layer-of-origin attribution
        ran (``util.health.attribute_nonfinite``) — ``layer``,
        ``quantity`` and ``param`` of the first offending value. Legacy
        3-argument overrides keep working: the guards fire through
        :func:`fire_step_skipped`, which degrades to the old signature."""
        pass


def fire_step_skipped(listener, model, iteration: int, reason: str,
                      info: Optional[dict] = None) -> None:
    """Fire ``on_step_skipped`` with the structured ``info`` dict,
    degrading to the legacy 3-argument signature for user listeners that
    predate it — the one copy of the adaptive call the guards and
    composite listeners share."""
    hook = getattr(listener, "on_step_skipped", None)
    if hook is None:
        return
    try:
        import inspect
        sig = inspect.signature(hook)
        takes_info = any(p.name == "info" or p.kind == p.VAR_KEYWORD
                         for p in sig.parameters.values())
    except (TypeError, ValueError):
        takes_info = False
    if takes_info:
        hook(model, iteration, reason, info=info)
    else:
        hook(model, iteration, reason)


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (parity: ScoreIterationListener.java)."""

    def __init__(self, print_iterations: int = 10,
                 log_fn: Optional[Callable[[str], None]] = None):
        self.print_iterations = max(1, int(print_iterations))
        self._log = log_fn or logger.info

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_iterations == 0:
            self._log(f"Score at iteration {iteration} is {float(score)}")


class PerformanceListener(TrainingListener):
    """Throughput reporting (parity: PerformanceListener.java:71-86 —
    samples/sec and batches/sec over the reporting window)."""

    def __init__(self, frequency: int = 10, report_batch: bool = True,
                 report_sample: bool = True,
                 log_fn: Optional[Callable[[str], None]] = None):
        self.frequency = max(1, int(frequency))
        self.report_batch = report_batch
        self.report_sample = report_sample
        self._log = log_fn or logger.info
        self._last_time = None
        self._last_iter = 0
        self._samples = 0
        self.last_samples_per_sec: Optional[float] = None
        self.last_batches_per_sec: Optional[float] = None

    def record_batch(self, batch_size: int) -> None:
        self._samples += int(batch_size)

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0
            return
        if (iteration - self._last_iter) >= self.frequency:
            dt = max(now - self._last_time, 1e-9)
            batches = iteration - self._last_iter
            self.last_batches_per_sec = batches / dt
            self.last_samples_per_sec = self._samples / dt
            parts = []
            if self.report_batch:
                parts.append(f"{self.last_batches_per_sec:.2f} batches/sec")
            if self.report_sample and self._samples:
                parts.append(f"{self.last_samples_per_sec:.2f} samples/sec")
            self._log(f"iteration {iteration}: " + ", ".join(parts))
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0


class CollectScoresIterationListener(TrainingListener):
    """Accumulate (iteration, score) pairs in memory
    (parity: CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class ComposableIterationListener(TrainingListener):
    """Fan one callback out to many (parity: ComposableIterationListener.java)."""

    def __init__(self, *listeners: TrainingListener):
        self.listeners = list(listeners)

    def record_batch(self, batch_size: int) -> None:
        for l in self.listeners:
            if hasattr(l, "record_batch"):
                l.record_batch(batch_size)

    def iteration_done(self, model, iteration, score):
        for l in self.listeners:
            l.iteration_done(model, iteration, score)

    def on_epoch_start(self, model, epoch):
        for l in self.listeners:
            l.on_epoch_start(model, epoch)

    def on_epoch_end(self, model, epoch):
        for l in self.listeners:
            l.on_epoch_end(model, epoch)

    def on_forward_pass(self, model, activations):
        for l in self.listeners:
            l.on_forward_pass(model, activations)

    def on_gradient_calculation(self, model):
        for l in self.listeners:
            l.on_gradient_calculation(model)

    def on_backward_pass(self, model):
        for l in self.listeners:
            l.on_backward_pass(model)

    def on_step_skipped(self, model, iteration, reason, info=None):
        for l in self.listeners:
            fire_step_skipped(l, model, iteration, reason, info)


class MetricsListener(TrainingListener):
    """Bridge training events into a
    :class:`~deeplearning4j_tpu.util.metrics.MetricsRegistry`: iteration
    and epoch counters, a last-score gauge, an iteration-wall-time
    histogram, and skipped-step counts from the resilience-guarded
    trainers — the scrapeable twin of StatsListener (which feeds the UI).

    Reading ``score`` forces a device sync (same caveat as
    ScoreIterationListener); ``frequency=N`` reads it only every Nth
    iteration (the counters and wall-time histogram stay per-step — they
    never touch the device), and ``record_score=False`` keeps the
    listener entirely off the async dispatch path.
    """

    _ITER_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, registry=None, name: str = "net",
                 record_score: bool = True, frequency: int = 1):
        from ..util import metrics as _metrics
        reg = registry if registry is not None else _metrics.REGISTRY
        self.registry = reg
        self.name = name
        self.record_score = record_score
        self.frequency = max(1, int(frequency))
        self._iterations = reg.counter(
            "training_iterations_total", "Training iterations completed",
            ("model",))
        self._epochs = reg.counter(
            "training_epochs_total", "Training epochs completed", ("model",))
        self._skipped = reg.counter(
            "training_steps_skipped_total",
            "Steps skipped by the non-finite guard; `layer` names the "
            "attributed origin (empty when attribution did not run)",
            ("model", "layer"))
        self._score = reg.gauge(
            "training_score", "Score at the latest iteration", ("model",))
        self._iter_time = reg.histogram(
            "training_iteration_seconds",
            "Wall time between consecutive iterations", ("model",),
            buckets=self._ITER_BUCKETS)
        self._last_time: Optional[float] = None

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        self._iterations.inc(model=self.name)
        if self._last_time is not None:
            self._iter_time.observe(now - self._last_time, model=self.name)
        self._last_time = now
        if self.record_score and iteration % self.frequency == 0:
            self._score.set(float(score), model=self.name)

    def on_epoch_end(self, model, epoch):
        self._epochs.inc(model=self.name)

    def on_step_skipped(self, model, iteration, reason, info=None):
        layer = (info or {}).get("layer") or ""
        self._skipped.inc(model=self.name, layer=layer)


class ParamAndGradientIterationListener(TrainingListener):
    """Log per-layer parameter and update magnitudes every N iterations
    (parity: ``ParamAndGradientIterationListener.java``).

    The reference prints parameter and raw-gradient statistics; here the
    UPDATE magnitude (parameter delta across the iteration) stands in for
    the gradient, which never leaves the fused jitted train step. Columns:
    mean |param|, mean |update|, and their ratio — the classic
    learning-rate sanity signal (~1e-3 is healthy).

    Use with ``fit``/``fit_batch``. The fused-scan paths
    (``fit_scan``/``fit_repeated``) replay listener fires AFTER all K
    updates landed, so deltas there are 0 — the listener prints a hint
    instead of a misleading zero signal.
    """

    def __init__(self, print_iterations: int = 10,
                 log_fn: Optional[Callable[[str], None]] = None):
        self.print_iterations = max(1, int(print_iterations))
        self._log = log_fn or logger.info
        self._prev = None

    @staticmethod
    def _flat(model):
        import jax
        import numpy as np
        return {jax.tree_util.keystr(path):
                np.asarray(leaf, dtype=np.float32)
                for path, leaf in
                jax.tree_util.tree_flatten_with_path(model.params)[0]}

    def iteration_done(self, model, iteration, score) -> None:
        import numpy as np

        prints = iteration % self.print_iterations == 0
        snapshots = (iteration + 1) % self.print_iterations == 0
        if not (prints or snapshots):
            return
        flat = self._flat(model)
        if prints:
            prev = self._prev or {}
            lines = []
            deltas = []
            for name, a in flat.items():
                p_mag = float(np.mean(np.abs(a)))
                if name in prev:
                    u_mag = float(np.mean(np.abs(a - prev[name])))
                    deltas.append(u_mag)
                    ratio = u_mag / (p_mag + 1e-12)
                    lines.append(f"  {name}: |p|={p_mag:.3e} "
                                 f"|Δp|={u_mag:.3e} ratio={ratio:.2e}")
                else:
                    lines.append(f"  {name}: |p|={p_mag:.3e}")
            if deltas and max(deltas) == 0.0:
                lines.append(
                    "  (all deltas are exactly 0 — fused-scan replay? "
                    "fit_scan/fit_repeated apply updates before listeners "
                    "fire; use fit/fit_batch with this listener)")
            self._log(f"iteration {iteration} param/update stats:\n"
                      + "\n".join(lines))
        if snapshots:
            # the iteration right before the next print: its delta to the
            # printed params is ONE update's magnitude
            self._prev = flat
