"""Convex optimizers: LBFGS, conjugate gradient, line gradient descent.

Parity: reference ``optimize/Solver.java:41-48`` (dispatch on
``OptimizationAlgorithm``), ``solvers/StochasticGradientDescent.java``,
``LBFGS.java``, ``ConjugateGradient.java``, ``LineGradientDescent.java``,
``BackTrackLineSearch.java``.

TPU-native design: these are full-batch deterministic optimizers over the
*flattened* parameter vector (``ravel_pytree``), with the loss+grad evaluated
as one jitted program. The minibatch path (the reference's SGD solver +
updaters) lives in the network runtimes; these solvers cover the reference's
second-order/line-search surface (used for small-data full-batch fits).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


class BackTrackLineSearch:
    """Armijo backtracking (parity: ``BackTrackLineSearch.java`` — step
    halving until sufficient decrease, maxIterations bounded)."""

    def __init__(self, c1: float = 1e-4, shrink: float = 0.5,
                 max_iterations: int = 5):
        self.c1 = float(c1)
        self.shrink = float(shrink)
        self.max_iterations = int(max_iterations)

    def search(self, f, x: jnp.ndarray, fx: float, g: jnp.ndarray,
               direction: jnp.ndarray, initial_step: float = 1.0
               ) -> Tuple[float, float]:
        """Returns (step, f(x + step*direction))."""
        slope = float(jnp.vdot(g, direction))
        step = initial_step
        best_step, best_val = 0.0, fx
        for _ in range(self.max_iterations):
            val = float(f(x + step * direction))
            if val <= fx + self.c1 * step * slope:
                return step, val
            if val < best_val:
                best_step, best_val = step, val
            step *= self.shrink
        return best_step, best_val


class Solver:
    """Full-batch solver over a network + one batch (parity: ``Solver.java``).

    Usage::

        Solver(net).optimize(x, y, iterations=50)   # algo from conf

    The algorithm comes from ``conf.training.optimization_algo``:
    ``"lbfgs" | "conjugate_gradient" | "line_gradient_descent"``
    (``"sgd"`` delegates to the network's own minibatch fit).
    """

    def __init__(self, net, algo: Optional[str] = None,
                 memory: int = 10, line_search: Optional[BackTrackLineSearch] = None):
        self.net = net
        self.algo = (algo or net.training.optimization_algo or "sgd").lower()
        self.memory = int(memory)
        self.line_search = line_search or BackTrackLineSearch(
            max_iterations=getattr(net.training, "max_line_search_iterations", 5))

    def _flat_loss(self, x, y, mask=None):
        net = self.net
        states = net._states_list() if hasattr(net, "_states_list") \
            else net._states_map()
        flat0, unravel = ravel_pytree(net.params)

        if hasattr(net, "_states_list"):
            def loss_tree(params):
                val, _ = net._loss_fn(params, states, x, y, mask, None)
                return val
        else:
            gmasks = None if mask is None else [mask]
            def loss_tree(params):
                val, _ = net._loss_fn(params, states, [x], [y], gmasks, None)
                return val

        loss_flat = jax.jit(lambda v: loss_tree(unravel(v)))
        grad_flat = jax.jit(jax.grad(lambda v: loss_tree(unravel(v))))
        return flat0, unravel, loss_flat, grad_flat

    def optimize(self, x, y, mask=None, iterations: Optional[int] = None,
                 tolerance: float = 1e-8) -> float:
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        iters = iterations or self.net.training.iterations or 10
        if self.algo in ("sgd", "stochastic_gradient_descent"):
            loss = None
            for _ in range(iters):
                loss = self.net.fit_batch(x, y, mask)
            return float(loss)
        flat0, unravel, f, g = self._flat_loss(x, y, mask)
        if self.algo == "lbfgs":
            final, score = self._lbfgs(flat0, f, g, iters, tolerance)
        elif self.algo in ("cg", "conjugate_gradient"):
            final, score = self._cg(flat0, f, g, iters, tolerance)
        elif self.algo in ("line_gradient_descent", "linegd"):
            final, score = self._line_gd(flat0, f, g, iters, tolerance)
        else:
            raise ValueError(f"unknown optimization algorithm {self.algo!r}")
        self.net.params = unravel(final)
        self.net._score = score
        return float(score)

    # ---- algorithms ----

    def _line_gd(self, x0, f, g, iters, tol):
        x = x0
        fx = float(f(x))
        for _ in range(iters):
            grad = g(x)
            step, fnew = self.line_search.search(f, x, fx, grad, -grad,
                                                 initial_step=1.0)
            if step == 0.0 or abs(fx - fnew) < tol:
                break
            x = x - step * grad
            fx = fnew
        return x, fx

    def _cg(self, x0, f, g, iters, tol):
        """Polak-Ribière nonlinear CG with restart (parity:
        ``ConjugateGradient.java``)."""
        x = x0
        fx = float(f(x))
        grad = g(x)
        direction = -grad
        for _ in range(iters):
            step, fnew = self.line_search.search(f, x, fx, grad, direction,
                                                 initial_step=1.0)
            if step == 0.0 or abs(fx - fnew) < tol:
                break
            x = x + step * direction
            new_grad = g(x)
            beta = float(jnp.vdot(new_grad, new_grad - grad)
                         / jnp.maximum(jnp.vdot(grad, grad), 1e-30))
            beta = max(0.0, beta)  # PR+ restart
            direction = -new_grad + beta * direction
            if float(jnp.vdot(direction, new_grad)) > 0:  # not a descent dir
                direction = -new_grad
            grad, fx = new_grad, fnew
        return x, fx

    def _lbfgs(self, x0, f, g, iters, tol):
        """Two-loop-recursion L-BFGS (parity: ``LBFGS.java``, memory m)."""
        m = self.memory
        x = x0
        fx = float(f(x))
        grad = g(x)
        s_hist: List[jnp.ndarray] = []
        y_hist: List[jnp.ndarray] = []
        for _ in range(iters):
            # two-loop recursion for H·g
            q = grad
            alphas = []
            for s, yv in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / float(jnp.maximum(jnp.vdot(yv, s), 1e-30))
                a = rho * float(jnp.vdot(s, q))
                alphas.append((a, rho, s, yv))
                q = q - a * yv
            if y_hist:
                s_last, y_last = s_hist[-1], y_hist[-1]
                gamma = float(jnp.vdot(s_last, y_last)
                              / jnp.maximum(jnp.vdot(y_last, y_last), 1e-30))
                q = gamma * q
            for a, rho, s, yv in reversed(alphas):
                b = rho * float(jnp.vdot(yv, q))
                q = q + (a - b) * s
            direction = -q
            step, fnew = self.line_search.search(f, x, fx, grad, direction,
                                                 initial_step=1.0)
            if step == 0.0 or abs(fx - fnew) < tol:
                break
            x_new = x + step * direction
            new_grad = g(x_new)
            s_hist.append(x_new - x)
            y_hist.append(new_grad - grad)
            if len(s_hist) > m:
                s_hist.pop(0)
                y_hist.pop(0)
            x, grad, fx = x_new, new_grad, fnew
        return x, fx
