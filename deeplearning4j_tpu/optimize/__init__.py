"""Training orchestration: updaters, LR schedules, gradient normalization,
listeners.

Parity target: reference ``optimize/`` (``Solver.java:41``,
``solvers/BaseOptimizer.java``, ``solvers/StochasticGradientDescent.java``)
and ``nn/updater/LayerUpdater.java:132-266``.

TPU-native design: an updater is a pair of pure functions
``(init(params) -> state, update(grads, state, params, iteration) -> (deltas,
state))`` — pytree-in/pytree-out, jit-friendly, optimizer state donated along
with params in the network train step. The reference's Solver/ConvexOptimizer
iteration loop collapses into the network's single jitted train step; the
LBFGS/CG solvers' line-search machinery is intentionally replaced by
first-order updaters (the TPU-idiomatic training path).
"""

from .updaters import (
    Updater,
    make_updater,
    learning_rate_at,
    normalize_gradients,
    apply_updates,
)
from .listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    CollectScoresIterationListener,
    MetricsListener,
    ParamAndGradientIterationListener,
    ComposableIterationListener,
)

__all__ = [
    "Updater", "make_updater", "learning_rate_at", "normalize_gradients",
    "apply_updates", "TrainingListener", "ScoreIterationListener",
    "PerformanceListener", "CollectScoresIterationListener",
    "MetricsListener", "ParamAndGradientIterationListener",
    "ComposableIterationListener",
]
