"""RNG utilities: named, splittable randomness.

The reference threads a long `seed` through NeuralNetConfiguration
(reference ``nn/conf/NeuralNetConfiguration.java:483``) into ND4J's global RNG.
TPU-native equivalent: functional `jax.random` keys, derived deterministically
by name so that parameter init and dropout streams are stable across replicas
and across process restarts (required for multi-host determinism).
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp


def key(seed: int = 0) -> jax.Array:
    return jax.random.PRNGKey(seed)


def _name_to_int(name: str) -> int:
    # Stable 32-bit hash (Python's hash() is salted per-process).
    return int.from_bytes(hashlib.blake2s(name.encode(), digest_size=4).digest(), "big")


def fold_name(k: jax.Array, name: str) -> jax.Array:
    """Derive a sub-key deterministically from a string name."""
    return jax.random.fold_in(k, _name_to_int(name))


def split_named(k: jax.Array, names) -> dict:
    return {n: fold_name(k, n) for n in names}


def uniform(k, shape, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(k, shape, dtype=dtype, minval=low, maxval=high)


def normal(k, shape, mean=0.0, std=1.0, dtype=jnp.float32):
    return mean + std * jax.random.normal(k, shape, dtype=dtype)
