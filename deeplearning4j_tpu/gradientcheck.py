"""Gradient checking: numeric central-difference vs autodiff.

Parity: reference ``gradientcheck/GradientCheckUtil.java:58`` (MultiLayerNetwork)
/ ``:171`` (ComputationGraph) — per-parameter central differences in double
precision compared against the analytic gradient with a relative-error
threshold. This is the reference's correctness backbone (its gradient-check
test suites cover every layer type); here it doubles as a check that
``jax.grad`` through our *forward* implementations matches the math — i.e.
that the forwards themselves are differentiable and correctly composed with
preprocessors, masks, regularization, and BN train-mode statistics.

Usage (mirrors ``GradientCheckUtil.checkGradients``)::

    from deeplearning4j_tpu.gradientcheck import check_gradients
    result = check_gradients(conf, x, y)           # conf is re-run in float64
    assert result.passed, result.summary()
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_EPS = 1e-6
DEFAULT_MAX_REL_ERROR = 1e-5
# below this absolute difference the relative error is not meaningful
# (reference GradientCheckUtil minAbsoluteError semantics)
DEFAULT_MIN_ABS_ERROR = 1e-9


@dataclasses.dataclass
class GradCheckFailure:
    param: str
    index: Tuple[int, ...]
    analytic: float
    numeric: float
    rel_error: float


@dataclasses.dataclass
class GradCheckResult:
    passed: bool
    n_checked: int
    max_rel_error: float
    failures: List[GradCheckFailure]

    def summary(self) -> str:
        lines = [f"gradient check: {'PASS' if self.passed else 'FAIL'} "
                 f"({self.n_checked} entries, max rel err {self.max_rel_error:.3e})"]
        for f in self.failures[:20]:
            lines.append(f"  {f.param}{list(f.index)}: analytic={f.analytic:.6e} "
                         f"numeric={f.numeric:.6e} rel={f.rel_error:.3e}")
        if len(self.failures) > 20:
            lines.append(f"  ... and {len(self.failures) - 20} more")
        return "\n".join(lines)


def _f64_network(conf):
    """Fresh float64 network from a (deep-copied) config — gradient checks
    run in double precision like the reference's."""
    from .nn.multilayer import MultiLayerNetwork

    conf64 = copy.deepcopy(conf)
    conf64.training.dtype = "float64"
    return MultiLayerNetwork(conf64).init()


def _check_loss_fn(loss, params, eps, max_rel_error, min_abs_error,
                   max_per_param, seed):
    """Shared core: compare jax.grad(loss) against central differences."""
    loss_jit = jax.jit(loss)
    grads = jax.jit(jax.grad(loss))(params)

    flat_params, treedef = jax.tree_util.tree_flatten_with_path(params)
    flat_grads = jax.tree_util.tree_leaves(grads)
    rng = np.random.default_rng(seed)

    failures: List[GradCheckFailure] = []
    n_checked = 0
    max_rel = 0.0
    params_np = jax.tree_util.tree_map(lambda a: np.array(a, dtype=np.float64),
                                       params)

    for (path, leaf), g in zip(flat_params, flat_grads):
        name = jax.tree_util.keystr(path)
        leaf_np = np.array(leaf, dtype=np.float64)
        g_np = np.array(g, dtype=np.float64)
        n = leaf_np.size
        idxs = np.arange(n)
        if max_per_param is not None and n > max_per_param:
            idxs = rng.choice(n, size=max_per_param, replace=False)
        leaf_ref = _find_leaf(params_np, path)
        for flat_idx in idxs:
            idx = np.unravel_index(flat_idx, leaf_np.shape)
            orig = leaf_np[idx]
            leaf_ref[idx] = orig + eps
            f_plus = float(loss_jit(params_np))
            leaf_ref[idx] = orig - eps
            f_minus = float(loss_jit(params_np))
            leaf_ref[idx] = orig

            numeric = (f_plus - f_minus) / (2.0 * eps)
            analytic = float(g_np[idx])
            denom = max(abs(numeric), abs(analytic))
            abs_err = abs(numeric - analytic)
            rel = 0.0 if denom == 0.0 else abs_err / denom
            n_checked += 1
            if abs_err > min_abs_error and rel > max_rel_error:
                failures.append(GradCheckFailure(name, tuple(int(i) for i in idx),
                                                 analytic, numeric, rel))
            if abs_err > min_abs_error:
                max_rel = max(max_rel, rel)

    return GradCheckResult(passed=not failures, n_checked=n_checked,
                           max_rel_error=max_rel, failures=failures)


def _find_leaf(tree, path):
    node = tree
    for p in path:
        if hasattr(p, "key"):
            node = node[p.key]
        elif hasattr(p, "idx"):
            node = node[p.idx]
        else:
            raise TypeError(f"unsupported path entry {p!r}")
    return node


def check_gradients(conf, x, y, mask=None, *,
                    epsilon: float = DEFAULT_EPS,
                    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                    max_per_param: Optional[int] = None,
                    seed: int = 0) -> GradCheckResult:
    """Gradient-check a MultiLayerConfiguration on one batch.

    The config is re-instantiated under a float64 dtype policy. Configs under
    test must not use dropout (non-deterministic between the two loss
    evaluations) — same constraint as the reference's checks.
    """
    net = _f64_network(conf)
    x64 = jnp.asarray(x, jnp.float64)
    y64 = jnp.asarray(y, jnp.float64)
    m64 = None if mask is None else jnp.asarray(mask, jnp.float64)
    states = net._states_list()

    def loss(params):
        val, _ = net._loss_fn(params, states, x64, y64, m64, None)
        return val

    return _check_loss_fn(loss, net.params, epsilon, max_rel_error,
                          min_abs_error, max_per_param, seed)


def check_graph_gradients(conf, inputs, labels, masks=None, *,
                          epsilon: float = DEFAULT_EPS,
                          max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                          min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                          max_per_param: Optional[int] = None,
                          seed: int = 0) -> GradCheckResult:
    """Gradient-check a ComputationGraphConfiguration (parity:
    ``GradientCheckUtil.java:171``)."""
    from .nn.graph_runtime import ComputationGraph

    conf64 = copy.deepcopy(conf)
    conf64.training.dtype = "float64"
    net = ComputationGraph(conf64).init()
    inputs64 = [jnp.asarray(a, jnp.float64) for a in _as_list(inputs)]
    labels64 = [jnp.asarray(a, jnp.float64) for a in _as_list(labels)]
    masks64 = (None if masks is None
               else [None if m is None else jnp.asarray(m, jnp.float64)
                     for m in _as_list(masks)])

    def loss(params):
        val, _ = net._loss_fn(params, net._states_map(), inputs64, labels64,
                              masks64, None)
        return val

    return _check_loss_fn(loss, net.params, epsilon, max_rel_error,
                          min_abs_error, max_per_param, seed)


def _as_list(v) -> List[Any]:
    return list(v) if isinstance(v, (list, tuple)) else [v]
