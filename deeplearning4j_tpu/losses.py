"""Loss functions (ILossFunction parity).

The reference delegates loss computation to ND4J ``ILossFunction`` impls
(used from ``nn/layers/BaseOutputLayer.java:92-115``): each computes a score
and a hand-written gradient w.r.t. pre-output. Here each loss is a pure
function of (labels, pre_output) — gradients come from ``jax.grad``; the
softmax/sigmoid + cross-entropy pairs are fused in logit space for numerical
stability (what the reference achieves by special-casing inside LossMCXENT).

Naming parity with the reference's LossFunction enum: MSE, L2, MAE/L1, XENT,
MCXENT, NEGATIVELOGLIKELIHOOD, HINGE, SQUARED_HINGE, KL_DIVERGENCE, MAPE,
MSLE, POISSON, COSINE_PROXIMITY.

Per-example semantics (matching the ND4J impls):
  L2   = sum_j (y-yhat)^2        MSE  = L2 / n_outputs
  L1   = sum_j |y-yhat|          MAE  = L1 / n_outputs
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .nn import activations as _act

EPS = 1e-7

# A loss fn maps (labels, pre_output, activation_name) -> per-(example,output)
# loss array of the same shape as labels (before any mask/reduction).
LossFn = Callable[[jax.Array, jax.Array, str], jax.Array]

_REGISTRY: Dict[str, LossFn] = {}


def register(*names: str):
    def deco(fn):
        for n in names:
            _REGISTRY[n.lower()] = fn
        return fn
    return deco


def get(name: str) -> LossFn:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; known: {sorted(_REGISTRY)}") from None


def names():
    return sorted(_REGISTRY)


def _activate(pre, activation):
    return _act.get(activation)(pre)


@register("mse", "squared_loss")
def mse(labels, pre, activation):
    d = _activate(pre, activation) - labels
    return d * d / labels.shape[-1]


@register("l2")
def l2(labels, pre, activation):
    d = _activate(pre, activation) - labels
    return d * d


@register("mae", "mean_absolute_error")
def mae(labels, pre, activation):
    return jnp.abs(_activate(pre, activation) - labels) / labels.shape[-1]


@register("l1")
def l1(labels, pre, activation):
    return jnp.abs(_activate(pre, activation) - labels)


@register("xent", "binary_xent", "binary_crossentropy", "reconstruction_crossentropy")
def xent(labels, pre, activation):
    """Binary cross-entropy. Fused in logit space when activation is sigmoid."""
    if activation.lower() == "sigmoid":
        # -[y*log sig(x) + (1-y)*log(1-sig(x))] = max(x,0) - x*y + log(1+exp(-|x|))
        return jnp.maximum(pre, 0) - pre * labels + jnp.log1p(jnp.exp(-jnp.abs(pre)))
    p = jnp.clip(_activate(pre, activation), EPS, 1.0 - EPS)
    return -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))


@register("mcxent", "negativeloglikelihood", "categorical_crossentropy")
def mcxent(labels, pre, activation):
    """Multi-class cross-entropy. Fused log-softmax when activation is softmax."""
    if activation.lower() == "softmax":
        logp = jax.nn.log_softmax(pre, axis=-1)
        return -labels * logp
    p = jnp.clip(_activate(pre, activation), EPS, 1.0 - EPS)
    return -labels * jnp.log(p)


@register("sparse_mcxent", "sparse_categorical_crossentropy")
def sparse_mcxent(labels, pre, activation):
    """Integer-class cross-entropy: ``labels`` holds CLASS IDS (shape =
    pre.shape minus the class axis, e.g. [b, t] ids against [b, t, V]
    logits) — the realistic-vocab path for LM training, where a one-hot
    [b, t, V] label tensor at V ≫ 1k would dominate host/device memory.
    Same per-row value as ``mcxent`` on the equivalent one-hot labels.
    Requires the fused softmax head (no dense-probability fallback: a
    clipped-log path would silently lose the log-space stability that is
    the point of this loss).

    Out-of-range ids (e.g. a tokenizer emitting V against a V-sized
    head) yield NaN loss entries instead of XLA's silent gather clamp to
    class V−1 — an off-by-one vocab bug must fail LOUDLY (non-finite
    loss, caught by skip budgets/watchdogs), not train quietly against
    the wrong class."""
    if activation.lower() != "softmax":
        raise ValueError("sparse_mcxent requires activation='softmax' "
                         f"(got {activation!r})")
    logp = jax.nn.log_softmax(pre, axis=-1)
    ids = labels.astype(jnp.int32)
    return -jnp.take_along_axis(logp, ids[..., None], axis=-1,
                                mode="fill", fill_value=jnp.nan)[..., 0]


@register("hinge")
def hinge(labels, pre, activation):
    # labels in {-1, +1}
    out = _activate(pre, activation)
    return jnp.maximum(0.0, 1.0 - labels * out)


@register("squared_hinge")
def squared_hinge(labels, pre, activation):
    h = hinge(labels, pre, activation)
    return h * h


@register("kl_divergence", "kld")
def kld(labels, pre, activation):
    p = jnp.clip(_activate(pre, activation), EPS, 1.0 - EPS)
    y = jnp.clip(labels, EPS, 1.0)
    return y * (jnp.log(y) - jnp.log(p))


@register("mape", "mean_absolute_percentage_error")
def mape(labels, pre, activation):
    out = _activate(pre, activation)
    return 100.0 * jnp.abs((labels - out) / jnp.where(jnp.abs(labels) < EPS, EPS, labels)) / labels.shape[-1]


@register("msle", "mean_squared_logarithmic_error")
def msle(labels, pre, activation):
    out = _activate(pre, activation)
    d = jnp.log1p(jnp.maximum(out, -1 + EPS)) - jnp.log1p(jnp.maximum(labels, -1 + EPS))
    return d * d / labels.shape[-1]


@register("poisson")
def poisson(labels, pre, activation):
    out = jnp.maximum(_activate(pre, activation), EPS)
    return out - labels * jnp.log(out)


@register("cosine_proximity")
def cosine_proximity(labels, pre, activation):
    out = _activate(pre, activation)
    ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
    on = jnp.linalg.norm(out, axis=-1, keepdims=True)
    cos = jnp.sum(labels * out, axis=-1, keepdims=True) / jnp.maximum(ln * on, EPS)
    # Broadcast so the per-element array keeps labels' shape; sum over features
    # then yields n_out * (-cos)/n_out = -cos per example.
    return -cos * jnp.ones_like(labels) / labels.shape[-1]


def score_array(loss_name: str, labels, pre_output, activation: str,
                mask: Optional[jax.Array] = None) -> jax.Array:
    """Per-example loss (summed over output features), mask applied.

    mask may be None, shape [batch], or broadcastable to labels' shape —
    matching the reference's per-output and per-timestep mask handling.
    """
    per_elem = get(loss_name)(labels, pre_output, activation)
    if mask is not None:
        m = mask
        while m.ndim < per_elem.ndim:
            m = m[..., None]
        per_elem = per_elem * m
    # sum over all non-batch axes
    axes = tuple(range(1, per_elem.ndim))
    return jnp.sum(per_elem, axis=axes) if axes else per_elem


def is_sparse(loss_name: str) -> bool:
    """True for losses whose labels are CLASS IDS (no class axis) rather
    than per-output arrays — changes the mask-ndim contract below."""
    return loss_name.lower() in ("sparse_mcxent",
                                 "sparse_categorical_crossentropy")


def masked_denominator(mask: Optional[jax.Array], labels,
                       batch_size: int, *, sparse: bool = False) -> jax.Array:
    """The averaging denominator under the explicit mask-kind contract
    (single source of truth — used by both :func:`score` and the network
    runtime's loss):
      - mask is None — the batch size.
      - mask.ndim <  labels.ndim — a per-row mask ([b] or [b,t]); each entry
        covers one example/timestep, so the denominator is ``sum(mask)``.
      - mask.ndim == labels.ndim — a per-output mask; a row counts as active
        if ANY of its outputs is unmasked, so the denominator is
        ``sum(any(mask, axis=-1))``.
    ``sparse=True`` (id-labeled losses — :func:`is_sparse`) declares that
    labels carry NO class axis, so an equal-ndim mask is per-row there,
    exactly like its dense one-hot equivalent — declared by the caller
    from the loss identity, never sniffed from the label dtype (a dense
    loss fed integer-typed labels must keep the per-output contract)."""
    if mask is None:
        return jnp.float32(batch_size)
    if mask.ndim == labels.ndim and not sparse:
        row_active = jnp.max(mask, axis=-1)    # per-output mask
        return jnp.maximum(jnp.sum(row_active), 1.0)
    return jnp.maximum(jnp.sum(mask), 1.0)     # per-row (example/timestep)


def score(loss_name: str, labels, pre_output, activation: str,
          mask: Optional[jax.Array] = None, average: bool = True) -> jax.Array:
    """Scalar loss. With a mask, averaging divides by the active row count
    (parity with reference masked-score semantics in BaseOutputLayer);
    see :func:`masked_denominator` for the mask-kind contract.
    """
    arr = score_array(loss_name, labels, pre_output, activation, mask)
    total = jnp.sum(arr)
    if not average:
        return total
    return total / masked_denominator(mask, labels, labels.shape[0],
                                      sparse=is_sparse(loss_name))
