"""DeepWalk: vertex embeddings from random walks.

Parity: reference ``models/deepwalk/DeepWalk.java`` (skip-gram with
hierarchical softmax over degree-weighted Huffman codes —
``GraphHuffman.java``) on walks from ``RandomWalkIterator``.

TPU-native: walks are token sequences ("0", "1", ...) fed to the same
vectorized SequenceVectors engine as Word2Vec; HS is the default to match the
reference, negative sampling available as an option.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..nlp.sequence_vectors import SequenceVectors
from .graph import Graph
from .walks import RandomWalkIterator


class DeepWalk:
    """Builder-style API (reference: ``DeepWalk.Builder`` —
    ``vectorSize``, ``windowSize``, ``learningRate``, walk length)."""

    def __init__(self, *, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, walk_length: int = 40,
                 walks_per_vertex: int = 4, epochs: int = 1,
                 negative: int = 0, seed: int = 42, batch_size: int = 4096):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.epochs = epochs
        self.negative = negative
        self.seed = seed
        self.batch_size = batch_size
        self._sv: Optional[SequenceVectors] = None
        self._n_vertices = 0

    def fit(self, graph: Graph) -> "DeepWalk":
        walks = RandomWalkIterator(graph, self.walk_length, seed=self.seed,
                                   walks_per_vertex=self.walks_per_vertex)
        token_walks = [[str(v) for v in walk] for walk in walks]
        self._n_vertices = graph.num_vertices()
        self._sv = SequenceVectors(
            layer_size=self.vector_size, window=self.window_size,
            negative=self.negative, learning_rate=self.learning_rate,
            epochs=self.epochs, seed=self.seed, batch_size=self.batch_size,
            min_word_frequency=1)
        self._sv.fit(token_walks)
        return self

    # -- lookup --
    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self._sv.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verticies_nearest(self, v: int, top: int = 10) -> List[int]:
        return [int(w) for w in self._sv.words_nearest(str(v), top=top)]
