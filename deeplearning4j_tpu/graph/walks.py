"""Random-walk iterators (parity: reference ``iterator/RandomWalkIterator.java``
— uniform next-vertex choice, NoEdgeHandling SELF_LOOP_ON_DISCONNECTED — and
``WeightedRandomWalkIterator.java`` — edge-weight-proportional choice)."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from .graph import Graph


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex."""

    def __init__(self, graph: Graph, walk_length: int,
                 seed: Optional[int] = None, walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.seed = seed
        self.walks_per_vertex = int(walks_per_vertex)

    def _next_vertex(self, rng, v: int) -> int:
        nbrs = self.graph.neighbors(v)
        if not nbrs:
            return v  # self-loop on disconnected (reference NoEdgeHandling)
        return int(nbrs[rng.integers(0, len(nbrs))])

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            order = rng.permutation(self.graph.num_vertices())
            for start in order:
                walk = [int(start)]
                v = int(start)
                for _ in range(self.walk_length - 1):
                    v = self._next_vertex(rng, v)
                    walk.append(v)
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Next vertex ∝ edge weight (parity: ``WeightedRandomWalkIterator``)."""

    def _next_vertex(self, rng, v: int) -> int:
        nbrs = self.graph.neighbors_weighted(v)
        if not nbrs:
            return v
        weights = np.array([w for _, w in nbrs], dtype=np.float64)
        probs = weights / weights.sum()
        return int(nbrs[rng.choice(len(nbrs), p=probs)][0])
