"""Adjacency-list graph (parity: reference ``graph/graph/Graph.java`` over
``api/IGraph.java`` — vertices 0..n-1, optional edge weights, directed or
undirected)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


class Graph:
    def __init__(self, n_vertices: int, directed: bool = False):
        self.n = int(n_vertices)
        self.directed = directed
        self._adj: List[List[Tuple[int, float]]] = [[] for _ in range(self.n)]

    def num_vertices(self) -> int:
        return self.n

    def add_edge(self, a: int, b: int, weight: float = 1.0) -> None:
        if not (0 <= a < self.n and 0 <= b < self.n):
            raise ValueError(f"edge ({a},{b}) out of range for n={self.n}")
        self._adj[a].append((b, float(weight)))
        if not self.directed and a != b:
            self._adj[b].append((a, float(weight)))

    def neighbors(self, v: int) -> List[int]:
        return [u for u, _ in self._adj[v]]

    def neighbors_weighted(self, v: int) -> List[Tuple[int, float]]:
        return list(self._adj[v])

    def degree(self, v: int) -> int:
        return len(self._adj[v])

    def num_edges(self) -> int:
        total = sum(len(a) for a in self._adj)
        return total if self.directed else total // 2
