"""Graph embeddings: structures, random walks, DeepWalk.

Parity: reference ``deeplearning4j-graph`` — ``graph/api/IGraph.java`` /
``graph/graph/Graph.java`` (adjacency-list digraph), ``data/GraphLoader.java``
(edge-list files), ``iterator/RandomWalkIterator.java`` /
``WeightedRandomWalkIterator.java``, ``models/deepwalk/DeepWalk.java``
(skip-gram-with-HS over walks) + ``GraphHuffman.java``.

TPU-native: walks are generated host-side (numpy), then embedded with the
same vectorized SequenceVectors engine as Word2Vec (walks are just token
sequences) — replacing the reference's per-edge gemv updates.
"""

from .deepwalk import DeepWalk
from .graph import Graph
from .loader import GraphLoader
from .walks import RandomWalkIterator, WeightedRandomWalkIterator

__all__ = ["Graph", "GraphLoader", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "DeepWalk"]
