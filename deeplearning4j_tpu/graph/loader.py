"""Graph loaders (parity: reference ``data/GraphLoader.java`` +
``DelimitedEdgeLineProcessor`` — edge-list text files)."""

from __future__ import annotations

from typing import Optional

from .graph import Graph


class GraphLoader:
    @staticmethod
    def load_undirected_graph_edge_list_file(
            path: str, n_vertices: int, delimiter: str = ",") -> Graph:
        return GraphLoader._load(path, n_vertices, delimiter, directed=False)

    @staticmethod
    def load_directed_graph_edge_list_file(
            path: str, n_vertices: int, delimiter: str = ",") -> Graph:
        return GraphLoader._load(path, n_vertices, delimiter, directed=True)

    @staticmethod
    def _load(path: str, n_vertices: int, delimiter: str,
              directed: bool) -> Graph:
        g = Graph(n_vertices, directed=directed)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                if len(parts) < 2:
                    continue
                a, b = int(parts[0]), int(parts[1])
                w = float(parts[2]) if len(parts) > 2 else 1.0
                g.add_edge(a, b, w)
        return g
