"""Failure recovery: checkpointed training that survives preemption.

Parity-plus: the reference has no bespoke fault tolerance — multi-node
recovery is delegated to Spark task retry/lineage (SURVEY §5) and the
single-process path just dies. On TPU pods preemption is routine, so the
framework owns the story: atomic rolling checkpoints (params + updater
state + counters via ``ModelSerializer``) and a ``fit`` wrapper that
resumes from the newest checkpoint, skipping completed epochs.

Exactness contract: for a SEEKABLE data source (the ``state()``/
``restore()`` cursor protocol every in-tree iterator implements — see
``util.durable``), ``RecoverableTrainer`` writes mid-epoch
:class:`~deeplearning4j_tpu.util.durable.TrainingState` snapshots that
carry the data-source cursor, and resume is bit-exact AT ANY STEP: the
restored run replays zero batches, skips none, and reproduces the
uninterrupted run's loss trajectory and final params bit-for-bit (pinned
by the kill-at-every-seam chaos tests in ``tests/test_durable.py``).
Legacy ``periodic_*``/``checkpoint_*`` zips are still written for
compatibility; non-seekable sources fall back to epoch-boundary resume
(the newest ``checkpoint_*``, re-running nothing).
"""

from __future__ import annotations

import logging
import os
import re
from typing import List, Optional

from . import faults as _faults
from .serialization import (CheckpointInvalid, load_model, save_model,
                            verify_checkpoint)

logger = logging.getLogger("deeplearning4j_tpu")

_KIND_RES = {
    "boundary": re.compile(r"^checkpoint_epoch(\d+)_iter(\d+)\.zip$"),
    "periodic": re.compile(r"^periodic_epoch(\d+)_iter(\d+)\.zip$"),
}


class CheckpointRecovery:
    """Rolling checkpoint store in one directory (single writer).

    ``latest()`` picks the newest checkpoint by (epoch, iteration);
    ``restore()`` / ``latest_valid()`` additionally validate integrity
    (zip CRC + sha256 manifest) and fall back to the newest VALID one, so
    a corrupt or truncated latest never blocks recovery. ``save(net)``
    writes atomically (tmp + rename) and prunes each kind to ``keep``
    newest — a crash mid-write never corrupts a recovery point. Stale
    ``.tmp_*``/``.wip_*`` files from crashed writers are swept on
    construction (the directory has one writer at a time by contract).
    """

    def __init__(self, directory: str, keep: int = 2):
        self.directory = directory
        self.keep = max(1, int(keep))
        os.makedirs(directory, exist_ok=True)
        for name in os.listdir(directory):
            if name.startswith((".tmp_", ".wip_")):
                try:
                    os.remove(os.path.join(directory, name))
                except OSError:
                    pass

    def _checkpoints(self, kind: str) -> List[str]:
        rx = _KIND_RES[kind]
        out = [n for n in os.listdir(self.directory) if rx.match(n)]
        out.sort(key=lambda n: tuple(map(int, rx.match(n).groups())))
        return out

    def latest(self, kind: str = "boundary") -> Optional[str]:
        cps = self._checkpoints(kind)
        return os.path.join(self.directory, cps[-1]) if cps else None

    def latest_valid(self, kind: str = "boundary") -> Optional[str]:
        """Newest checkpoint that passes integrity validation (zip CRC +
        checksum manifest). Invalid files — truncated by a torn write,
        flipped bytes, empty — are skipped with a warning, so a corrupt
        latest never blocks recovery while an older valid point exists."""
        for name in reversed(self._checkpoints(kind)):
            path = os.path.join(self.directory, name)
            try:
                verify_checkpoint(path)
                return path
            except CheckpointInvalid as e:
                logger.warning(
                    "skipping corrupt checkpoint %s (%s) — falling back "
                    "to the previous one", path, e)
        return None

    def save(self, net, kind: str = "boundary") -> str:
        prefix = "checkpoint" if kind == "boundary" else "periodic"
        name = (f"{prefix}_epoch{net.epoch_count}"
                f"_iter{net.iteration_count}.zip")
        final = os.path.join(self.directory, name)
        tmp = os.path.join(self.directory, f".tmp_{os.getpid()}_{name}")
        save_model(net, tmp, save_updater=True)
        os.replace(tmp, final)
        for stale in self._checkpoints(kind)[:-self.keep]:
            try:
                os.remove(os.path.join(self.directory, stale))
            except OSError:
                pass
        return final

    def restore(self, kind: str = "boundary"):
        """Newest VALID checkpointed model of the given kind, or None.
        Corrupt/truncated checkpoints are skipped (see
        :meth:`latest_valid`); a checkpoint that validates but still fails
        to load falls back to the next older one the same way."""
        for name in reversed(self._checkpoints(kind)):
            path = os.path.join(self.directory, name)
            try:
                verify_checkpoint(path)
                _faults.check("recovery.restore", {"path": path})
                return load_model(path, load_updater=True)
            except Exception as e:
                logger.warning(
                    "checkpoint %s unusable (%s: %s) — falling back to "
                    "the previous one", path, type(e).__name__, e)
        return None


class RecoverableTrainer:
    """``fit`` with automatic resume (the TPU-native answer to Spark task
    retry): restores the newest recovery point on construction, then
    trains the remaining epochs, checkpointing every ``frequency``
    iterations and at each epoch end.

    Recovery points, newest-wins: durable ``state_*`` snapshots
    (``util.durable.TrainingState`` — params + updater + RNG counters +
    data cursor; exact at any step) and legacy epoch-boundary
    ``checkpoint_*`` zips. A mid-epoch snapshot resumes EXACTLY when
    ``fit`` is then given a seekable data source: the cursor is restored
    and the partial epoch continues from the precise batch where the
    process died."""

    def __init__(self, net, checkpoint_dir: str, *, frequency: int = 100,
                 keep: int = 2):
        from . import durable as _durable
        self.recovery = CheckpointRecovery(checkpoint_dir, keep=keep)
        self.store = _durable.CheckpointStore(checkpoint_dir, keep=keep)
        self._resume_cursor: Optional[dict] = None
        restored = None
        # every candidate, newest-wins by the (epoch, iteration) in its
        # NAME (no model deserialization just to compare recency; durable
        # snapshots win ties — they carry the cursor). A candidate that
        # validates but fails to load falls back to the next older one
        # ACROSS kinds — never silently past a newer valid snapshot.
        for _, kind, path in self._recovery_points():
            try:
                if kind == "durable":
                    loaded = self.store.load(path)
                    restored = loaded.net
                    self._resume_cursor = loaded.cursor
                else:
                    verify_checkpoint(path)
                    _faults.check("recovery.restore", {"path": path})
                    restored = load_model(path, load_updater=True)
                break
            except Exception as e:
                self._resume_cursor = None
                logger.warning(
                    "recovery point %s unusable (%s: %s) — falling back "
                    "to the next older one", path, type(e).__name__, e)
        if restored is not None:
            net = restored
        self.net = net
        self.frequency = max(1, int(frequency))
        self.resumed = restored is not None

    def _recovery_points(self) -> List[tuple]:
        """All recovery points in the directory, newest first:
        ``((epoch, iter, durable?), kind, path)`` for durable ``state_*``
        snapshot dirs and legacy boundary ``checkpoint_*`` zips."""
        from . import durable as _durable
        points = []
        for name in self.store.snapshots():
            m = _durable._STATE_RE.match(name)
            points.append(((int(m.group(1)), int(m.group(2)), 1),
                           "durable",
                           os.path.join(self.store.directory, name)))
        for name in self.recovery._checkpoints("boundary"):
            e, i = self._parse(name)
            points.append(((e, i, 0), "legacy",
                           os.path.join(self.recovery.directory, name)))
        points.sort(reverse=True)
        return points

    @staticmethod
    def _parse(path: str) -> tuple:
        m = _KIND_RES["boundary"].match(os.path.basename(path))
        return tuple(map(int, m.groups())) if m else (-1, -1)

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None):
        """Train until ``epochs`` TOTAL epochs are recorded on the model
        (a resumed model with epoch_count >= epochs trains zero epochs).
        A mid-epoch resume restores the data cursor first — the source
        must then be seekable (every in-tree iterator is)."""
        from . import durable as _durable
        net = self.net
        kwargs = _durable.mask_fit_kwargs(net, mask)
        resumed_mid = self._resume_cursor is not None
        if resumed_mid:
            if not _durable.is_seekable(data):
                raise ValueError(
                    "resuming a mid-epoch snapshot needs a seekable data "
                    f"source (state()/restore()) — got "
                    f"{type(data).__name__}")
            data.restore(self._resume_cursor)
            self._resume_cursor = None
        hook = _CheckpointListener(self.recovery, net, self.frequency)
        net.add_listener(hook)
        seekable = _durable.is_seekable(data)
        writer = (_durable.AsyncCheckpointWriter(self.store)
                  if seekable else None)
        try:
            while net.epoch_count < epochs:
                if seekable:
                    # exact mid-epoch recovery points (cursor-carrying
                    # TrainingState snapshots) ride along with the legacy
                    # periodic zips, written off the critical path
                    kwargs["session"] = _durable.DurableSession(
                        net, self.store, data=data,
                        frequency=self.frequency, writer=writer,
                        resuming=resumed_mid)
                    resumed_mid = False
                net.fit(data, labels, epochs=1, **kwargs)
                self.recovery.save(net, kind="boundary")
                if hasattr(data, "reset"):
                    data.reset()
        finally:
            net.listeners.remove(hook)
            if writer is not None:
                writer.close()
        return net


class _CheckpointListener:
    """TrainingListener shim writing a checkpoint every N iterations."""

    def __init__(self, recovery: CheckpointRecovery, net, frequency: int):
        self.recovery = recovery
        self.net = net
        self.frequency = frequency

    def iteration_done(self, model, iteration: int, score) -> None:
        if iteration % self.frequency == 0:
            self.recovery.save(self.net, kind="periodic")

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass

    def on_forward_pass(self, model, activations) -> None:
        pass

    def on_gradient_calculation(self, model) -> None:
        pass

    def on_backward_pass(self, model) -> None:
        pass
