"""Training-health telemetry: on-device per-layer stats, a health-rules
engine, and NaN layer-of-origin attribution.

Parity-plus: the reference's L7 observability surface (StatsListener →
StatsStorage → UI) computes param/gradient/update statistics HOST-side —
``ui/stats.py::StatsListener._param_stats`` device_gets every tensor to
histogram it, reintroducing the per-step host syncs the async-dispatch
work removed. Here the model-internals statistics are computed INSIDE the
jitted train step (the same dispatch that applies the update):

- :func:`model_stats` — the per-layer stats pytree the stats-enabled
  train steps return: param/grad/update L2 norms, update:param ratio,
  activation mean/std + zero-fraction (dead-ReLU), per-layer non-finite
  gradient counts, and fixed-edge log-bucket histograms (edges are
  compile-time constants, so the histogram adds no retrace and no
  data-dependent shapes).
- :class:`DeviceStats` — LazyScore-style wrapper: the pytree stays on
  device until a consumer reads ``.value()``, which performs ONE
  device→host transfer (counted in ``training_host_syncs_total``). The
  step loss rides inside the pytree, so a listener window costs exactly
  one sync — score included.
- :class:`HealthEngine` + :func:`default_rules` — turns snapshots into
  per-rule ok/warn/critical verdicts (vanishing/exploding gradients
  across depth, dead units, update:param ratio band, loss-divergence
  trend, non-finite gradients), published as
  ``training_health_state{model,rule,layer}`` gauges,
  ``model_stats_*{model,layer}`` gauges, and ``health_state`` flight
  events on every transition.
- :func:`attribute_nonfinite` — the NaN layer-of-origin protocol: when a
  step is skipped for non-finite gradients, replay the failing batch
  through per-layer finite checks (inputs → params → activations in
  forward order → gradients in backward order, i.e. closest to the loss
  first, since activation NaNs propagate forward and gradient NaNs
  propagate backward) and name the first offending layer/param.
- :func:`debug_payload` — the ``GET /debug/health`` body served by
  UIServer and InferenceServer: latest rule report, latest stats
  snapshot, latest attribution.

Per-layer label cardinality is bounded by model DEPTH (layer keys /
vertex names), never by width or vocab, so the metric families stay
inside the exposition lint's series budget.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import math
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from . import flightrecorder as _flight

logger = logging.getLogger("deeplearning4j_tpu")

Pytree = Any

# The model-wide entry in a stats pytree (total grad norm, non-finite
# count, the step loss). Layer keys never collide with it: both runtimes
# name layers "layer_N" / by vertex name.
MODEL_KEY = "_model_"

OK, WARN, CRITICAL = "ok", "warn", "critical"
HEALTH_STATE_VALUES = {OK: 0.0, WARN: 1.0, CRITICAL: 2.0}
_SEVERITY = {OK: 0, WARN: 1, CRITICAL: 2}

# Fixed log10(|x|) bucket edges: [-12, 4] in 16 buckets, plus an
# underflow bucket (zeros and |x| < 1e-12) and an overflow bucket
# (|x| > 1e4 and non-finite values). Fixed edges — unlike numpy's
# data-dependent min/max — make the histogram a pure reduction with a
# static shape, so it compiles into the train step once.
HIST_LOG_LO = -12.0
HIST_LOG_HI = 4.0
HIST_LOG_BUCKETS = 16
HIST_LEN = HIST_LOG_BUCKETS + 2


def histogram_edges() -> np.ndarray:
    """The log10 bucket edges (host-side; for rendering/labels)."""
    return np.linspace(HIST_LOG_LO, HIST_LOG_HI, HIST_LOG_BUCKETS + 1)


# ----------------------------------------------------------------------
# on-device reductions (called inside the jitted train step)
# ----------------------------------------------------------------------

def _inexact_leaves(tree: Pytree) -> List[Any]:
    import jax
    import jax.numpy as jnp
    return [l for l in jax.tree_util.tree_leaves(tree)
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact)]


def log_histogram(x) -> Any:
    """int32[HIST_LEN] counts of |x| over the fixed log10 edges.
    Bucket 0 = zeros/underflow; bucket HIST_LEN-1 = overflow AND
    non-finite values (so a NaN-poisoned tensor is visible in the
    histogram too, not just in the non-finite counter).

    Implemented as HIST_LEN masked reductions over a bucket-index array
    rather than a scatter-add: XLA lowers the scatter serially (~100ns/
    element on CPU — it dominated the whole stats pass), while the
    compare+sum loop fuses into one vectorized sweep."""
    import jax.numpy as jnp
    ax = jnp.abs(jnp.ravel(x).astype(jnp.float32))
    finite = jnp.isfinite(ax)
    step = (HIST_LOG_HI - HIST_LOG_LO) / HIST_LOG_BUCKETS
    logs = jnp.log10(jnp.where(ax > 0, ax, 1.0))
    idx = jnp.floor((logs - HIST_LOG_LO) / step).astype(jnp.int32) + 1
    idx = jnp.clip(idx, 0, HIST_LEN - 1)
    idx = jnp.where(ax > 0, idx, 0)
    idx = jnp.where(finite, idx, HIST_LEN - 1)
    return jnp.stack([jnp.sum((idx == b).astype(jnp.int32))
                      for b in range(HIST_LEN)])


def tree_l2(tree: Pytree) -> Any:
    """float32 L2 norm over every inexact leaf of a pytree."""
    import jax.numpy as jnp
    leaves = _inexact_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    total = jnp.float32(0.0)
    for l in leaves:
        total = total + jnp.sum(jnp.square(l.astype(jnp.float32)))
    return jnp.sqrt(total)


def tree_nonfinite_count(tree: Pytree) -> Any:
    """int32 count of non-finite elements across a pytree."""
    import jax.numpy as jnp
    leaves = _inexact_leaves(tree)
    if not leaves:
        return jnp.int32(0)
    total = jnp.int32(0)
    for l in leaves:
        total = total + jnp.sum(~jnp.isfinite(l)).astype(jnp.int32)
    return total


def tree_histogram(tree: Pytree) -> Any:
    """Summed :func:`log_histogram` over every inexact leaf."""
    import jax.numpy as jnp
    leaves = _inexact_leaves(tree)
    if not leaves:
        return jnp.zeros(HIST_LEN, jnp.int32)
    counts = jnp.zeros(HIST_LEN, jnp.int32)
    for l in leaves:
        counts = counts + log_histogram(l)
    return counts


def act_summary(a, sample: int = 0) -> Dict[str, Any]:
    """Per-layer activation scalars, gradient-stopped so collecting them
    cannot perturb the backward pass. Two-moment std (E[x²]−E[x]²) keeps
    it at two sweeps over the activation instead of jnp.std's
    mean-subtract-square re-read. ``sample`` > 0 reduces over only the
    first ``sample`` batch rows — the health rules need estimates, not
    exact moments, and a 64-example sample keeps the reductions off the
    critical path at large batch (the slice is static, so no retrace)."""
    import jax
    import jax.numpy as jnp
    af = jax.lax.stop_gradient(a)
    if sample and hasattr(af, "shape") and af.ndim >= 1 \
            and af.shape[0] > sample:
        af = af[:sample]
    af = af.astype(jnp.float32)
    m = jnp.mean(af)
    m2 = jnp.mean(jnp.square(af))
    return {"act_mean": m,
            "act_std": jnp.sqrt(jnp.maximum(m2 - jnp.square(m), 0.0)),
            "act_zero_frac": jnp.mean((af == 0.0).astype(jnp.float32))}


@dataclasses.dataclass(frozen=True)
class StatsConfig:
    """What the stats-enabled train step collects. Part of the jit cache
    key (``trace_key``), so flipping a field retraces under the new
    collection set without touching the cached no-stats trace.
    ``act_sample`` bounds the batch rows the activation moments reduce
    over (0 = all rows)."""

    histograms: bool = True
    activations: bool = True
    act_sample: int = 64

    def trace_key(self) -> str:
        return (f"h{int(self.histograms)}a{int(self.activations)}"
                f"s{int(self.act_sample)}")

    @staticmethod
    def coerce(value) -> Optional["StatsConfig"]:
        if value is None or value is False:
            return None
        if value is True:
            return StatsConfig()
        if isinstance(value, StatsConfig):
            return value
        raise TypeError(
            f"health stats config must be True/False/None/StatsConfig, "
            f"got {type(value).__name__}")


def value_grad_with_stats(loss_fn, config: Optional[StatsConfig],
                          params, *args):
    """``jax.value_and_grad`` over a runtime ``_loss_fn``, optionally in
    stats-collecting mode — the ONE copy of the collect/aux-unpack dance
    every train-step/scan/repeat body in both runtimes shares. Returns
    ``(loss, new_states, grads_raw, act_stats)`` with ``act_stats`` None
    when ``config`` is None (grads are RAW, pre-normalization — what
    :func:`model_stats` must see)."""
    import jax
    if config is not None:
        fn = functools.partial(loss_fn, collect_stats=config)
        (loss, (new_states, act_stats)), grads = jax.value_and_grad(
            fn, has_aux=True)(params, *args)
    else:
        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, *args)
        act_stats = None
    return loss, new_states, grads, act_stats


def model_stats(params: Dict[str, Pytree], grads: Dict[str, Pytree],
                deltas: Dict[str, Pytree],
                act_stats: Optional[Dict[str, Dict[str, Any]]],
                config: StatsConfig, *, loss=None) -> Dict[str, Dict]:
    """The per-layer stats pytree, computed INSIDE the jitted train step.

    ``params``/``grads``/``deltas`` are the runtimes' layer-keyed trees
    (params post-update — what you would checkpoint; grads RAW, before
    normalization — what the health rules must see); ``act_stats`` maps
    layer key → :func:`act_summary` output collected during the forward.
    Everything reduces to scalars (plus the fixed-width histograms), so
    the whole pytree is a few KB however wide the model is.
    """
    import jax.numpy as jnp
    tiny = jnp.float32(1e-12)
    out: Dict[str, Dict] = {}
    for name in params:
        entry: Dict[str, Any] = {}
        if _inexact_leaves(params[name]):
            pn = tree_l2(params[name])
            un = tree_l2(deltas[name])
            entry.update(
                param_norm=pn,
                grad_norm=tree_l2(grads[name]),
                update_norm=un,
                update_ratio=un / jnp.maximum(pn, tiny),
                grad_nonfinite=tree_nonfinite_count(grads[name]))
            if config.histograms:
                entry["param_hist"] = tree_histogram(params[name])
                entry["update_hist"] = tree_histogram(deltas[name])
        acts = None if act_stats is None else act_stats.get(name)
        if acts and config.activations:
            entry.update(acts)
        if entry:
            out[name] = entry
    model_entry: Dict[str, Any] = {
        "grad_norm": tree_l2(grads),
        "grad_nonfinite": tree_nonfinite_count(grads)}
    if loss is not None:
        model_entry["loss"] = jnp.asarray(loss, jnp.float32)
    out[MODEL_KEY] = model_entry
    return out


# ----------------------------------------------------------------------
# host-side consumption
# ----------------------------------------------------------------------

def to_jsonable(tree):
    """Host snapshot → plain python (floats/ints/lists), JSON-ready."""
    if isinstance(tree, dict):
        return {k: to_jsonable(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [to_jsonable(v) for v in tree]
    if isinstance(tree, np.ndarray):
        return tree.item() if tree.ndim == 0 else tree.tolist()
    if isinstance(tree, (np.floating, np.integer, np.bool_)):
        return tree.item()
    if hasattr(tree, "shape"):      # a stray device array
        arr = np.asarray(tree)
        return arr.item() if arr.ndim == 0 else arr.tolist()
    return tree


class DeviceStats:
    """A stats pytree that stays on device until read (the LazyScore of
    model internals). ``value()`` performs the single device→host
    transfer — counted into ``training_host_syncs_total`` — and caches
    the JSON-ready result, so a listener window costs exactly one sync
    however many consumers read the same snapshot."""

    __slots__ = ("_tree", "_host", "iteration", "model", "_registry")

    def __init__(self, tree: Pytree, *, iteration: int = 0,
                 model: str = "net", registry=None):
        self._tree = tree
        self._host: Optional[Dict] = None
        self.iteration = int(iteration)
        self.model = model
        self._registry = registry

    @property
    def resolved(self) -> bool:
        return self._host is not None

    def value(self) -> Dict[str, Dict]:
        if self._host is None:
            import jax
            from . import ingest as _ingest
            _ingest.sync_counter(self._registry).inc()
            tree, self._tree = self._tree, None
            self._host = to_jsonable(jax.device_get(tree))
        return self._host

    def __repr__(self) -> str:
        return (f"DeviceStats(iteration={self.iteration}, "
                f"{'resolved' if self.resolved else '<on device>'})")


def latest_stats(net) -> Optional[DeviceStats]:
    """The most recent :class:`DeviceStats` a stats-enabled train step
    stored on the net (None when stats are off or nothing ran yet)."""
    return getattr(net, "_last_health_stats", None)


def layer_items(stats: Dict[str, Dict]):
    """(layer, entry) pairs excluding the model-wide entry, in depth
    order (dict insertion order = the runtimes' layer order)."""
    return [(k, v) for k, v in stats.items() if k != MODEL_KEY]


# ----------------------------------------------------------------------
# health rules
# ----------------------------------------------------------------------

class HealthSample(NamedTuple):
    """What a rule sees: the host stats snapshot, the iteration it was
    collected at, and the recent loss history (oldest first)."""
    stats: Dict[str, Dict]
    iteration: int
    losses: Tuple[float, ...]


class Verdict(NamedTuple):
    layer: str
    state: str
    detail: str


class HealthRule:
    """One diagnosis. ``evaluate`` returns a verdict per layer it judged
    (OK verdicts included, so the engine can record recoveries); an empty
    list means the rule had nothing to judge this sample."""

    name = "rule"

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        raise NotImplementedError


def _finite(v) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


class UpdateRatioRule(HealthRule):
    """update:param L2 ratio per layer. The classic LR-health band is
    ~[1e-4, 1e-2] (DL4J's visualization guide); outside it the layer is
    either frozen (too low) or thrashing (too high). Warmup iterations
    are skipped — the first Adam steps legitimately overshoot the band
    while the moment estimates bootstrap."""

    name = "update_ratio"

    def __init__(self, lo: float = 1e-4, hi: float = 1e-2,
                 critical_factor: float = 10.0, warmup: int = 10):
        self.lo, self.hi = float(lo), float(hi)
        self.critical_factor = float(critical_factor)
        self.warmup = int(warmup)

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        if sample.iteration < self.warmup:
            return []
        out = []
        for layer, e in layer_items(sample.stats):
            r, pn = e.get("update_ratio"), e.get("param_norm")
            if r is None or not pn:
                continue
            if not _finite(r) or r > self.hi * self.critical_factor:
                out.append(Verdict(layer, CRITICAL,
                                   f"update:param ratio {r:.3e} far above "
                                   f"the healthy band [{self.lo:g}, "
                                   f"{self.hi:g}]"))
            elif r < self.lo / self.critical_factor:
                out.append(Verdict(layer, WARN,
                                   f"update:param ratio {r:.3e} ~zero — "
                                   "layer effectively frozen"))
            elif r < self.lo or r > self.hi:
                out.append(Verdict(layer, WARN,
                                   f"update:param ratio {r:.3e} outside "
                                   f"[{self.lo:g}, {self.hi:g}]"))
            else:
                out.append(Verdict(layer, OK, ""))
        return out


class ExplodingGradientsRule(HealthRule):
    """Absolute per-layer gradient-norm blowup (an exploding run crosses
    these within a few steps; the depth RATIO is the vanishing rule's
    job)."""

    name = "exploding_gradients"

    def __init__(self, warn_norm: float = 1e3, critical_norm: float = 1e6):
        self.warn_norm = float(warn_norm)
        self.critical_norm = float(critical_norm)

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        out = []
        for layer, e in layer_items(sample.stats):
            gn = e.get("grad_norm")
            if gn is None:
                continue
            if not _finite(gn) or gn > self.critical_norm:
                out.append(Verdict(layer, CRITICAL,
                                   f"gradient norm {gn:.3e} exploding"))
            elif gn > self.warn_norm:
                out.append(Verdict(layer, WARN,
                                   f"gradient norm {gn:.3e} > "
                                   f"{self.warn_norm:g}"))
            else:
                out.append(Verdict(layer, OK, ""))
        return out


class VanishingGradientsRule(HealthRule):
    """Gradient attenuation ACROSS DEPTH: the ratio of the first param
    layer's grad norm to the last's. A healthy deep net keeps it within
    a few orders of magnitude; 1e-6 means the early layers see no
    learning signal."""

    name = "vanishing_gradients"

    def __init__(self, warn_ratio: float = 1e-6,
                 critical_ratio: float = 1e-9):
        self.warn_ratio = float(warn_ratio)
        self.critical_ratio = float(critical_ratio)

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        layered = [(k, e) for k, e in layer_items(sample.stats)
                   if _finite(e.get("grad_norm"))]
        if len(layered) < 2:
            return []
        first_layer, first = layered[0]
        last = layered[-1][1]
        if not last["grad_norm"]:
            return []
        ratio = first["grad_norm"] / last["grad_norm"]
        if ratio < self.critical_ratio:
            state, detail = CRITICAL, (
                f"first/last grad-norm ratio {ratio:.3e} — early layers "
                "receive no gradient")
        elif ratio < self.warn_ratio:
            state, detail = WARN, (
                f"first/last grad-norm ratio {ratio:.3e} < "
                f"{self.warn_ratio:g}")
        else:
            state, detail = OK, ""
        return [Verdict(first_layer, state, detail)]


class DeadUnitsRule(HealthRule):
    """Dead-unit (zero-activation) fraction per layer — the dead-ReLU
    detector. Judged only on layers that carried activation stats."""

    name = "dead_units"

    def __init__(self, warn_frac: float = 0.9, critical_frac: float = 0.99):
        self.warn_frac = float(warn_frac)
        self.critical_frac = float(critical_frac)

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        out = []
        for layer, e in layer_items(sample.stats):
            zf = e.get("act_zero_frac")
            if zf is None:
                continue
            if zf >= self.critical_frac:
                out.append(Verdict(layer, CRITICAL,
                                   f"{zf:.1%} of activations are exactly "
                                   "zero — layer is dead"))
            elif zf >= self.warn_frac:
                out.append(Verdict(layer, WARN,
                                   f"{zf:.1%} of activations are exactly "
                                   "zero"))
            else:
                out.append(Verdict(layer, OK, ""))
        return out


class NonFiniteGradientsRule(HealthRule):
    """Any non-finite gradient element is CRITICAL on its layer — the
    stats-plane twin of the NonFiniteGuard skip path."""

    name = "nonfinite_grads"

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        out = []
        for layer, e in layer_items(sample.stats):
            n = e.get("grad_nonfinite")
            if n is None:
                continue
            if n:
                out.append(Verdict(layer, CRITICAL,
                                   f"{int(n)} non-finite gradient "
                                   "elements"))
            else:
                out.append(Verdict(layer, OK, ""))
        return out


class LossDivergenceRule(HealthRule):
    """Loss-trend divergence over the engine's loss window: a non-finite
    loss is CRITICAL; a sustained rise (median of the newest samples vs
    the oldest) is WARN/CRITICAL by factor."""

    name = "loss_divergence"

    def __init__(self, window: int = 6, warn_factor: float = 4.0,
                 critical_factor: float = 100.0):
        self.window = int(window)
        self.warn_factor = float(warn_factor)
        self.critical_factor = float(critical_factor)

    def evaluate(self, sample: HealthSample) -> List[Verdict]:
        losses = sample.losses
        if not losses:
            return []
        if not math.isfinite(losses[-1]):
            return [Verdict(MODEL_KEY, CRITICAL,
                            f"loss is non-finite ({losses[-1]})")]
        if len(losses) < self.window:
            return [Verdict(MODEL_KEY, OK, "")]
        head = sorted(losses[:3])[1]    # median of oldest 3
        tail = sorted(losses[-3:])[1]   # median of newest 3
        if head > 0 and tail > head * self.critical_factor:
            return [Verdict(MODEL_KEY, CRITICAL,
                            f"loss rose {tail / head:.1f}x over the "
                            f"window ({head:.3e} -> {tail:.3e})")]
        if head > 0 and tail > head * self.warn_factor:
            return [Verdict(MODEL_KEY, WARN,
                            f"loss rose {tail / head:.1f}x over the "
                            f"window ({head:.3e} -> {tail:.3e})")]
        return [Verdict(MODEL_KEY, OK, "")]


def default_rules() -> List[HealthRule]:
    return [UpdateRatioRule(), ExplodingGradientsRule(),
            VanishingGradientsRule(), DeadUnitsRule(),
            NonFiniteGradientsRule(), LossDivergenceRule()]


# the stat fields mirrored into /metrics gauges (model_stats_* families)
_STAT_GAUGE_FIELDS = ("param_norm", "grad_norm", "update_ratio",
                      "act_zero_frac")


class HealthEngine:
    """Evaluates rules over stats snapshots; publishes gauges + flight
    events; keeps the latest report for ``GET /debug/health``.

    State machine: per (rule, layer), any change of verdict state records
    a ``health_state`` flight event (ok→warn escalations AND recoveries),
    so a post-mortem flight dump shows when each diagnosis flipped.
    """

    def __init__(self, rules: Optional[Sequence[HealthRule]] = None, *,
                 model: str = "net", registry=None, loss_window: int = 16,
                 publish_stats_gauges: bool = True):
        from . import metrics as _metrics
        reg = registry if registry is not None else _metrics.REGISTRY
        self.model = model
        self.rules = list(rules) if rules is not None else default_rules()
        self._state_gauge = reg.gauge(
            "training_health_state",
            "Health-rule verdict per rule and layer (0=ok, 1=warn, "
            "2=critical); layer label cardinality is bounded by model "
            "depth", ("model", "rule", "layer"))
        self._stat_gauges = None
        if publish_stats_gauges:
            self._stat_gauges = {
                "param_norm": reg.gauge(
                    "model_stats_param_norm",
                    "Per-layer parameter L2 norm from the on-device "
                    "stats pass", ("model", "layer")),
                "grad_norm": reg.gauge(
                    "model_stats_grad_norm",
                    "Per-layer raw-gradient L2 norm from the on-device "
                    "stats pass", ("model", "layer")),
                "update_ratio": reg.gauge(
                    "model_stats_update_ratio",
                    "Per-layer update:param L2 ratio from the on-device "
                    "stats pass", ("model", "layer")),
                "act_zero_frac": reg.gauge(
                    "model_stats_act_zero_frac",
                    "Per-layer zero-activation fraction (dead units) "
                    "from the on-device stats pass", ("model", "layer")),
            }
        self._losses: collections.deque = collections.deque(
            maxlen=max(2, int(loss_window)))
        self._states: Dict[Tuple[str, str], str] = {}
        self.last_report: Optional[Dict] = None

    def observe(self, stats: Dict[str, Dict], *,
                iteration: int = 0) -> Dict:
        """Feed one host snapshot (``DeviceStats.value()`` output).
        Returns the rule report and remembers it for /debug/health."""
        model_entry = stats.get(MODEL_KEY) or {}
        loss = model_entry.get("loss")
        if loss is not None:
            self._losses.append(float(loss))
        sample = HealthSample(stats=stats, iteration=int(iteration),
                              losses=tuple(self._losses))
        report_rules: Dict[str, Dict] = {}
        worst_overall = OK
        for rule in self.rules:
            try:
                verdicts = rule.evaluate(sample)
            except Exception:
                logger.exception("health rule %s failed", rule.name)
                continue
            if not verdicts:
                continue
            worst = OK
            flagged: Dict[str, Dict] = {}
            for v in verdicts:
                self._state_gauge.set(HEALTH_STATE_VALUES[v.state],
                                      model=self.model, rule=rule.name,
                                      layer=v.layer)
                key = (rule.name, v.layer)
                prev = self._states.get(key, OK)
                if v.state != prev:
                    _flight.record(
                        "health_state", model=self.model, rule=rule.name,
                        layer=v.layer, from_state=prev, to_state=v.state,
                        detail=v.detail, iteration=int(iteration))
                    if _SEVERITY[v.state] > _SEVERITY[prev]:
                        logger.warning(
                            "health rule %s %s on %s/%s: %s", rule.name,
                            v.state.upper(), self.model, v.layer, v.detail)
                self._states[key] = v.state
                if _SEVERITY[v.state] > _SEVERITY[worst]:
                    worst = v.state
                if v.state != OK:
                    flagged[v.layer] = {"state": v.state,
                                        "detail": v.detail}
            report_rules[rule.name] = {
                "state": worst, "layers": flagged,
                "evaluated": len(verdicts)}
            if _SEVERITY[worst] > _SEVERITY[worst_overall]:
                worst_overall = worst
        if self._stat_gauges is not None:
            for layer, e in layer_items(stats):
                for field, gauge in self._stat_gauges.items():
                    v = e.get(field)
                    if v is not None and _finite(v):
                        gauge.set(v, model=self.model, layer=layer)
        report = {"model": self.model, "iteration": int(iteration),
                  "state": worst_overall, "rules": report_rules,
                  "t": time.time()}
        self.last_report = report
        _remember_report(report, stats)
        return report


class HealthListener:
    """Training listener consuming the on-device stats every ``frequency``
    iterations: ONE host sync per window (the snapshot carries the loss,
    so the LazyScore is never read). Enables the stats pass on the model
    at attach time unless ``enable=False`` (then it only consumes stats
    someone else enabled). Duck-typed against the TrainingListener
    contract, like every listener the fit loop fires."""

    def __init__(self, frequency: int = 10,
                 engine: Optional[HealthEngine] = None,
                 model: str = "net", registry=None, config=True,
                 enable: bool = True):
        self.frequency = max(1, int(frequency))
        self.engine = (engine if engine is not None
                       else HealthEngine(model=model, registry=registry))
        self._config = StatsConfig.coerce(config) or StatsConfig()
        self._enable = enable
        self._last_observed = 0    # iteration of the last observed snapshot

    def _ensure_enabled(self, model) -> None:
        if (self._enable and getattr(model, "health_stats", None) is None
                and hasattr(model, "enable_health_stats")):
            model.enable_health_stats(self._config)

    def on_epoch_start(self, model, epoch: int) -> None:
        self._ensure_enabled(model)

    def on_epoch_end(self, model, epoch: int) -> None:
        pass

    def on_step_skipped(self, model, iteration, reason, info=None) -> None:
        pass

    def iteration_done(self, model, iteration: int, score) -> None:
        self._ensure_enabled(model)
        ds = latest_stats(model)
        # only observe a snapshot THIS iteration's dispatch produced:
        # fit_scan/fit_repeated fire listeners for window-interior
        # iterations whose snapshot belongs to the window's LAST step,
        # and a model whose stats stopped (disable, or a step variant
        # without them) would otherwise republish the frozen snapshot as
        # live data — same staleness guard as StatsListener's device path
        if ds is None or ds.iteration != iteration:
            return
        # cadence: exact frequency multiples on the per-step path, and
        # "at least frequency iterations since the last observation" so
        # scanned windows whose final iterations never align with the
        # frequency (k=16 @ frequency=10 → finals 16, 32, ...) still get
        # judged about every `frequency` iterations instead of only at
        # lcm(frequency, k)
        if (iteration % self.frequency
                and iteration - self._last_observed < self.frequency):
            return
        self._last_observed = iteration
        self.engine.observe(ds.value(), iteration=iteration)


# ----------------------------------------------------------------------
# NaN layer-of-origin attribution
# ----------------------------------------------------------------------

@dataclasses.dataclass
class AttributionReport:
    """Which layer a non-finite step originated at, and in what quantity
    (``input`` → ``param`` → ``activation`` → ``gradient`` — the order
    the protocol checks them in)."""

    model: str
    iteration: int
    quantity: str                       # input|param|activation|gradient|unknown
    layer: Optional[str] = None
    param: Optional[str] = None
    detail: str = ""

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        if self.layer is None:
            return f"first non-finite quantity: {self.quantity}"
        p = f".{self.param}" if self.param else ""
        return f"first non-finite {self.quantity} at {self.layer}{p}"


def _np_all_finite(a) -> bool:
    arr = np.asarray(a)
    if not np.issubdtype(arr.dtype, np.floating) \
            and not np.issubdtype(arr.dtype, np.complexfloating):
        return True
    return bool(np.isfinite(arr).all())


def _first_bad_param(tree) -> Optional[str]:
    if not isinstance(tree, dict):
        return None if _np_all_finite(tree) else ""
    for pname, leaf in tree.items():
        if hasattr(leaf, "dtype") and not _np_all_finite(leaf):
            return pname
    return None


def attribute_nonfinite(net, x, y=None, mask=None, *, params=None,
                        model: Optional[str] = None, iteration: int = 0,
                        record: bool = True) -> AttributionReport:
    """Replay a failing batch through per-layer finite checks and name the
    first offending layer/param.

    Protocol (each stage only runs if the previous found nothing):

    1. **inputs** — a poisoned batch is the most common culprit.
    2. **params**, forward order — a previously-corrupted checkpoint.
    3. **activations**, forward order (eval-mode diagnostic forward):
       activation NaNs propagate FORWARD, so the first non-finite layer
       output is the origin.
    4. **gradients**, BACKWARD order (one un-jitted ``jax.grad`` of the
       training loss): gradient NaNs propagate from the loss toward the
       input, so the origin is the non-finite layer CLOSEST to the loss.

    This is a failure path: it runs un-jitted, on demand, never in the
    hot loop. The report lands in the flight recorder and the
    ``/debug/health`` payload (``record=False`` to suppress)."""
    import jax
    import jax.numpy as jnp
    from .netutil import is_graph as _is_graph

    graph = _is_graph(net)
    params = params if params is not None else net.params
    model = model or type(net).__name__
    if graph:
        order = list(net.topo_order)
    else:
        order = [f"layer_{i}" for i in range(len(net.layers))]

    def _finish(quantity, layer=None, param=None, detail=""):
        report = AttributionReport(model=model, iteration=int(iteration),
                                   quantity=quantity, layer=layer,
                                   param=param, detail=detail)
        if record:
            _flight.record("nonfinite_attribution", model=model,
                           iteration=int(iteration), quantity=quantity,
                           layer=layer, param=param, detail=detail)
            _remember_attribution(report)
        return report

    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    if not graph:
        # sharded-trainer callers hand list-wrapped batches either way;
        # the sequential runtime's loss takes bare arrays
        if isinstance(y, (list, tuple)):
            y = y[0] if y else None
        if isinstance(mask, (list, tuple)):
            mask = mask[0] if mask else None
    for i, a in enumerate(xs):
        if a is not None and hasattr(a, "dtype") and not _np_all_finite(a):
            return _finish("input", detail=f"network input {i} carries "
                           "non-finite values")

    for name in order:
        bad = _first_bad_param(params.get(name) or {})
        if bad is not None:
            return _finish("param", layer=name, param=bad or None)

    # eval-mode diagnostic forward (deterministic: no dropout draws); a
    # train-mode-only NaN source then falls through to the gradient stage
    try:
        if graph:
            inputs = [jnp.asarray(a) for a in xs]
            acts, _ = net._forward(params, net._states_map(), inputs,
                                   train=False)
            per_layer = [(name, acts[name]) for name in order]
        else:
            acts, _ = net._forward(params, net._states_list(),
                                   jnp.asarray(xs[0]), train=False,
                                   collect=True)
            per_layer = [(order[i], acts[i + 1])
                         for i in range(len(acts) - 1)]
        for name, a in per_layer:
            if not _np_all_finite(a):
                return _finish("activation", layer=name)
    except Exception as e:
        logger.warning("attribution forward replay failed: %s", e)

    if y is not None:
        try:
            if graph:
                ys = [jnp.asarray(a) for a in
                      (y if isinstance(y, (list, tuple)) else [y])]
                ms = (None if mask is None else
                      [None if m is None else jnp.asarray(m) for m in
                       (mask if isinstance(mask, (list, tuple))
                        else [mask])])
                inputs = [jnp.asarray(a) for a in xs]
                grads = jax.grad(lambda p: net._loss_fn(
                    p, net._states_map(), inputs, ys, ms, None)[0])(params)
            else:
                grads = jax.grad(lambda p: net._loss_fn(
                    p, net._states_list(), jnp.asarray(xs[0]),
                    jnp.asarray(y),
                    None if mask is None else jnp.asarray(mask),
                    None)[0])(params)
            for name in reversed(order):
                bad = _first_bad_param(grads.get(name) or {})
                if bad is not None:
                    return _finish("gradient", layer=name,
                                   param=bad or None)
        except Exception as e:
            logger.warning("attribution gradient replay failed: %s", e)

    return _finish("unknown", detail="replay found every checked "
                   "quantity finite (transient, or a train-mode-only "
                   "source)")


# ----------------------------------------------------------------------
# /debug/health state
# ----------------------------------------------------------------------

_debug_lock = threading.Lock()
_last_report: Optional[Dict] = None
_last_stats: Optional[Dict] = None
_last_attribution: Optional[AttributionReport] = None


def _remember_report(report: Dict, stats: Dict) -> None:
    global _last_report, _last_stats
    with _debug_lock:
        _last_report = report
        _last_stats = stats


def _remember_attribution(report: AttributionReport) -> None:
    global _last_attribution
    with _debug_lock:
        _last_attribution = report


def last_attribution() -> Optional[AttributionReport]:
    with _debug_lock:
        return _last_attribution


def reset_debug_state() -> None:
    """Test hook: forget the remembered report/stats/attribution."""
    global _last_report, _last_stats, _last_attribution
    with _debug_lock:
        _last_report = _last_stats = _last_attribution = None


def debug_payload() -> Dict:
    """The ``GET /debug/health`` body: latest rule report, latest stats
    snapshot, latest NaN attribution (each None until produced)."""
    with _debug_lock:
        return {
            "report": _last_report,
            "stats": _last_stats,
            "attribution": (_last_attribution.to_dict()
                            if _last_attribution is not None else None),
            "histogram_log10_edges": histogram_edges().tolist(),
        }
