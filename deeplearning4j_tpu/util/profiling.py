"""Profiling / tracing helpers: device trace capture, step timing, MFU.

Parity: the reference's three timing systems (SURVEY §5) —
``PerformanceListener.java:71-86`` (samples/sec), the Spark phase timers
(``StatsUtils.java:69-92``), and StatsListener's fwd/bwd breakdown — plus
the capability the reference never had: capturing a compiler-level device
trace. TPU-native: wraps ``jax.profiler`` (XPlane traces viewable in
TensorBoard / Perfetto) and provides the analytic-FLOPs MFU arithmetic used
by bench.py, so users chase utilization the way PERF.md does.

On-demand capture (the TensorBoard-profiler "capture profile" button,
minus TensorBoard): :func:`capture_trace` records for N seconds under a
process-wide single-capture guard (:class:`ProfilerBusy` while one is
running — the serving/UI servers' ``POST /profile`` maps it to 409), and
:class:`StepCapture` is the piecewise form ``run_fit_loop`` uses to
bracket an exact step range (``DL4JTPU_PROFILE_STEPS=start:stop[:dir]``,
0-based, stop-exclusive) — production profiling with no code changes.
"""

from __future__ import annotations

import contextlib
import logging
import os
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets)
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,     # jax device_kind string for v5e
    "v5p": 459e12,
    "v6e": 918e12,
}


def peak_flops_per_sec(device=None) -> Optional[float]:
    """bf16 peak of the attached chip (first device by default), or None
    for an unknown device kind (CPU, GPU, a TPU generation not in the
    table) — callers decide what "no denominator" means for them: bench
    falls back to an assumed chip, :func:`mfu` raises asking for an
    explicit peak, and the live ``measured_mfu`` gauge degrades to a
    flops/sec gauge (util/ingest.py)."""
    import jax
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


def mfu(examples_per_sec: float, flops_per_example: float,
        peak: Optional[float] = None) -> float:
    """Model FLOPs utilization: useful analytic FLOPs over peak. The
    standard convention — no recompute/rematerialization inflation.
    Raises ValueError when no ``peak`` is given and the attached device's
    peak is unknown (CPU/unknown kinds have no meaningful MFU)."""
    if peak is None:
        peak = peak_flops_per_sec()
        if peak is None:
            import jax
            raise ValueError(
                f"unknown device kind "
                f"{getattr(jax.devices()[0], 'device_kind', '?')!r} has no "
                "published peak — pass peak= explicitly (MFU is undefined "
                "without a denominator)")
    return examples_per_sec * flops_per_example / peak


# ----------------------------------------------------------------------
# device trace capture (single-capture guard)
# ----------------------------------------------------------------------

class ProfilerBusy(RuntimeError):
    """A device-trace capture is already in progress (the profiler
    supports exactly one at a time). HTTP surfaces answer 409."""


# one capture at a time, process-wide: jax.profiler.start_trace raises on
# a second concurrent start, so the guard turns a crash into a clean
# "busy" the HTTP endpoints can answer as 409
_capture_lock = threading.Lock()


def capture_in_progress() -> bool:
    return _capture_lock.locked()


def _acquire_capture() -> None:
    if not _capture_lock.acquire(blocking=False):
        raise ProfilerBusy(
            "a profiler capture is already in progress (one at a time)")


def default_capture_dir() -> str:
    """Capture root: ``DL4JTPU_PROFILE_DIR`` or the system temp dir."""
    return (os.environ.get("DL4JTPU_PROFILE_DIR")
            or os.path.join(tempfile.gettempdir(), "dl4jtpu_profile"))


def _new_run_dir(log_dir: Optional[str]) -> str:
    d = os.path.join(
        log_dir or default_capture_dir(),
        f"capture_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    return d


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace (XPlane) into ``log_dir``; view in
    TensorBoard's profile plugin or Perfetto. Holds the single-capture
    guard: raises :class:`ProfilerBusy` if another capture is running."""
    import jax
    _acquire_capture()
    try:
        jax.profiler.start_trace(log_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
    finally:
        _capture_lock.release()


def capture_trace(seconds: float, log_dir: Optional[str] = None) -> str:
    """Blocking on-demand capture: trace whatever the process's devices do
    for the next ``seconds``, into a fresh timestamped run directory
    (under ``log_dir`` / ``DL4JTPU_PROFILE_DIR`` / the temp dir). Returns
    the run directory; raises :class:`ProfilerBusy` while another capture
    is running — the ``POST /profile?seconds=N`` implementation."""
    seconds = float(seconds)
    if not 0 < seconds <= 300:
        raise ValueError(f"seconds must be in (0, 300], got {seconds}")
    run_dir = _new_run_dir(log_dir)
    with trace(run_dir):
        time.sleep(seconds)
    return run_dir


class StepCapture:
    """Piecewise capture for ``run_fit_loop``'s step bracketing: the
    profiler starts before step ``start`` and stops after step ``stop-1``
    (two separate calls, possibly epochs apart), holding the
    single-capture guard for the whole window. A busy profiler skips the
    capture with a warning instead of failing the training run."""

    def __init__(self, log_dir: Optional[str] = None):
        self.log_dir = log_dir
        self.run_dir: Optional[str] = None
        self.active = False

    def start(self) -> bool:
        import jax
        try:
            _acquire_capture()
        except ProfilerBusy:
            logger.warning(
                "DL4JTPU_PROFILE_STEPS capture skipped: another profiler "
                "capture is in progress")
            return False
        try:
            self.run_dir = _new_run_dir(self.log_dir)
            jax.profiler.start_trace(self.run_dir)
        except Exception:
            _capture_lock.release()
            raise
        self.active = True
        logger.info("profiler capture started into %s", self.run_dir)
        return True

    def stop(self) -> Optional[str]:
        if not self.active:
            return None
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            self.active = False
            _capture_lock.release()
        logger.info("profiler capture written to %s", self.run_dir)
        return self.run_dir


def profile_request(query: Dict[str, list]) -> Tuple[dict, int]:
    """The ``POST /profile?seconds=N[&dir=...]`` implementation shared by
    the serving and UI servers: parse-qs style query dict in,
    (json body, http code) out. Blocks the calling handler thread for
    the capture window; a concurrent capture answers 409."""
    try:
        seconds = float(query.get("seconds", ["1"])[0])
    except (TypeError, ValueError) as e:
        return {"error": f"bad seconds: {e}"}, 400
    log_dir = query.get("dir", [None])[0]
    try:
        run_dir = capture_trace(seconds, log_dir)
    except ProfilerBusy as e:
        return {"error": str(e)}, 409
    except ValueError as e:
        return {"error": str(e)}, 400
    return {"ok": True, "dir": run_dir, "seconds": seconds}, 200


# (kind label, jax memory_stats key) for the device_memory_bytes gauge
_MEMORY_KINDS = (("in_use", "bytes_in_use"),
                 ("peak", "peak_bytes_in_use"),
                 ("limit", "bytes_limit"))


def register_device_memory_gauges(registry=None):
    """Per-device callback gauges ``device_memory_bytes{device,kind}``
    (kind = in_use/peak/limit) sampled live at exposition time — HBM
    pressure on ``/metrics``, not just the UI pane. Idempotent; on
    backends without ``memory_stats()`` (CPU) the callbacks raise at
    exposition and the series are dropped, leaving only the family
    header."""
    from . import metrics as _metrics
    reg = registry if registry is not None else _metrics.REGISTRY
    g = reg.gauge(
        "device_memory_bytes",
        "Per-device memory from the backend's memory_stats(), sampled at "
        "exposition time (kind: in_use/peak/limit; series absent when "
        "the backend exposes no stats)", ("device", "kind"))
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return g

    def sampler(dev, key):
        def fn() -> float:
            stats = dev.memory_stats()
            if not stats or key not in stats:
                raise LookupError(f"{key} unavailable on {dev}")
            return float(stats[key])
        return fn

    for d in devices:
        label = f"{d.platform}:{d.id}"
        for kind, key in _MEMORY_KINDS:
            g.set_function(sampler(d, key), device=label, kind=kind)
    return g


def profile_steps_env() -> Optional[Tuple[int, int, Optional[str]]]:
    """Parse ``DL4JTPU_PROFILE_STEPS=start:stop[:dir]`` (0-based step
    indices within one fit() call, stop-exclusive): the range of
    dispatched steps ``run_fit_loop`` brackets with a profiler capture.
    Returns (start, stop, dir) or None when unset."""
    raw = os.environ.get("DL4JTPU_PROFILE_STEPS", "").strip()
    if not raw:
        return None
    parts = raw.split(":", 2)
    if len(parts) < 2:
        raise ValueError(
            f"DL4JTPU_PROFILE_STEPS={raw!r} is not start:stop[:dir]")
    start, stop = int(parts[0]), int(parts[1])
    if start < 0 or stop <= start:
        raise ValueError(
            f"DL4JTPU_PROFILE_STEPS={raw!r}: need 0 <= start < stop")
    return start, stop, (parts[2] or None) if len(parts) > 2 else None


@dataclass
class StepTiming:
    mean_ms: float
    min_ms: float
    max_ms: float
    steps: int


def time_steps(step_fn: Callable[[], object], steps: int = 10,
               warmup: int = 2) -> StepTiming:
    """Wall-time a step callable with a proper device barrier per sample.

    The completion barrier is a device→host transfer of (a tiny slice of)
    the step result — on remote-attached devices ``block_until_ready`` can
    return before execution finishes (see bench.py), so a d2h read is the
    only trustworthy fence.
    """
    def run_once() -> float:
        t0 = time.perf_counter()
        out = step_fn()
        _barrier(out)
        return (time.perf_counter() - t0) * 1000.0

    for _ in range(warmup):
        run_once()
    samples = [run_once() for _ in range(steps)]
    return StepTiming(mean_ms=float(np.mean(samples)),
                      min_ms=float(np.min(samples)),
                      max_ms=float(np.max(samples)), steps=steps)


def _barrier(out) -> None:
    """d2h-read fence over EVERY device leaf of ``out`` — a multi-output
    step (params, opt_state, loss) can have its later outputs still
    executing when the first one lands, so fencing only the first leaf
    reports completion early."""
    import jax
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            flat = jax.numpy.ravel(leaf)
            np.asarray(flat[:1])
    # no device values returned: nothing to fence


# ----------------------------------------------------------------------
# Analytic FLOPs for common layer shapes (used by bench.py's configs)
# ----------------------------------------------------------------------

def conv2d_flops(out_h: int, out_w: int, kh: int, kw: int, cin: int,
                 cout: int) -> float:
    """MACs×2 for one example's conv forward."""
    return 2.0 * out_h * out_w * kh * kw * cin * cout


def dense_flops(n_in: int, n_out: int) -> float:
    return 2.0 * n_in * n_out


def lstm_flops(seq_len: int, n_in: int, hidden: int) -> float:
    """Gates: 4 matmuls of [n_in+hidden, hidden] per timestep."""
    return 2.0 * seq_len * 4 * (n_in + hidden) * hidden


def train_flops(forward_flops: float) -> float:
    """Training step ≈ 3× forward (fwd + dx + dW), the standard accounting."""
    return 3.0 * forward_flops
