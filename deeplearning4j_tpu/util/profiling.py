"""Profiling / tracing helpers: device trace capture, step timing, MFU.

Parity: the reference's three timing systems (SURVEY §5) —
``PerformanceListener.java:71-86`` (samples/sec), the Spark phase timers
(``StatsUtils.java:69-92``), and StatsListener's fwd/bwd breakdown — plus
the capability the reference never had: capturing a compiler-level device
trace. TPU-native: wraps ``jax.profiler`` (XPlane traces viewable in
TensorBoard / Perfetto) and provides the analytic-FLOPs MFU arithmetic used
by bench.py, so users chase utilization the way PERF.md does.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional

import numpy as np

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets)
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,     # jax device_kind string for v5e
    "v5p": 459e12,
    "v6e": 918e12,
}


def peak_flops_per_sec(device=None) -> float:
    """bf16 peak of the attached chip (first device by default)."""
    import jax
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return peak
    raise ValueError(
        f"unknown device kind {kind!r}; pass peak FLOPs explicitly")


def mfu(examples_per_sec: float, flops_per_example: float,
        peak: Optional[float] = None) -> float:
    """Model FLOPs utilization: useful analytic FLOPs over peak. The
    standard convention — no recompute/rematerialization inflation."""
    return examples_per_sec * flops_per_example / (peak
                                                   or peak_flops_per_sec())


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace (XPlane) into ``log_dir``; view in
    TensorBoard's profile plugin or Perfetto."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclass
class StepTiming:
    mean_ms: float
    min_ms: float
    max_ms: float
    steps: int


def time_steps(step_fn: Callable[[], object], steps: int = 10,
               warmup: int = 2) -> StepTiming:
    """Wall-time a step callable with a proper device barrier per sample.

    The completion barrier is a device→host transfer of (a tiny slice of)
    the step result — on remote-attached devices ``block_until_ready`` can
    return before execution finishes (see bench.py), so a d2h read is the
    only trustworthy fence.
    """
    def run_once() -> float:
        t0 = time.perf_counter()
        out = step_fn()
        _barrier(out)
        return (time.perf_counter() - t0) * 1000.0

    for _ in range(warmup):
        run_once()
    samples = [run_once() for _ in range(steps)]
    return StepTiming(mean_ms=float(np.mean(samples)),
                      min_ms=float(np.min(samples)),
                      max_ms=float(np.max(samples)), steps=steps)


def _barrier(out) -> None:
    import jax
    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            flat = jax.numpy.ravel(leaf)
            np.asarray(flat[:1])
            return
    # no device values returned: nothing to fence


# ----------------------------------------------------------------------
# Analytic FLOPs for common layer shapes (used by bench.py's configs)
# ----------------------------------------------------------------------

def conv2d_flops(out_h: int, out_w: int, kh: int, kw: int, cin: int,
                 cout: int) -> float:
    """MACs×2 for one example's conv forward."""
    return 2.0 * out_h * out_w * kh * kw * cin * cout


def dense_flops(n_in: int, n_out: int) -> float:
    return 2.0 * n_in * n_out


def lstm_flops(seq_len: int, n_in: int, hidden: int) -> float:
    """Gates: 4 matmuls of [n_in+hidden, hidden] per timestep."""
    return 2.0 * seq_len * 4 * (n_in + hidden) * hidden


def train_flops(forward_flops: float) -> float:
    """Training step ≈ 3× forward (fwd + dx + dW), the standard accounting."""
    return 3.0 * forward_flops
