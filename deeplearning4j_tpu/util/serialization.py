"""Checkpoint container: config + params + updater state in one artifact.

Parity: reference ``util/ModelSerializer.java:47-120`` — a zip with
``configuration.json``, ``coefficients.bin`` (params) and ``updaterState.bin``;
``:158-280`` ``restoreMultiLayerNetwork`` with ``loadUpdater`` flag giving
exact training resume.

TPU-native design: one ``.zip`` holding ``configuration.json`` plus a single
``arrays.npz`` with every leaf of the params / layer-state / updater-state
pytrees under path-encoded names (``params/layer_0/W``). Pytree *structure*
is rebuilt from the path names, so the artifact is a plain, inspectable
numpy archive — no pickling, no framework-version lock-in. Counters
(iteration/epoch/update) ride in ``training_state.json`` so resume is exact.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from . import faults as _faults

Pytree = Any

_CONFIG_ENTRY = "configuration.json"
_ARRAYS_ENTRY = "arrays.npz"
_STATE_ENTRY = "training_state.json"
_DTYPES_ENTRY = "dtypes.json"
_CHECKSUMS_ENTRY = "checksums.json"
_FORMAT_VERSION = 1


class CheckpointInvalid(ValueError):
    """The artifact at ``path`` is not a loadable checkpoint (truncated,
    corrupt, or missing required entries)."""


def _write_file_atomic(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via same-directory temp + rename, so a
    crash mid-write never leaves a partial file under the final name.
    ``faults`` seam: ``"checkpoint.write"`` (payload: {path, data}) —
    a scripted fault may raise before the write (clean failure) or
    emulate a torn writer itself."""
    _faults.check("checkpoint.write", {"path": path, "data": data})
    d, base = os.path.split(os.path.abspath(path))
    tmp = os.path.join(d, f".wip_{os.getpid()}_{base}")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def verify_checkpoint(path: str) -> None:
    """Validate a checkpoint artifact WITHOUT building the model: the file
    is a readable zip, every required entry is present, the zip CRCs check
    out, and (for artifacts that carry one) the sha256 manifest matches.
    Raises :class:`CheckpointInvalid` with the reason otherwise."""
    try:
        if os.path.getsize(path) == 0:
            raise CheckpointInvalid(f"{path}: empty file")
        with zipfile.ZipFile(path, "r") as zf:
            names = set(zf.namelist())
            missing = ({_CONFIG_ENTRY, _ARRAYS_ENTRY, _STATE_ENTRY}
                       - names)
            if missing:
                raise CheckpointInvalid(
                    f"{path}: missing entries {sorted(missing)}")
            if _CHECKSUMS_ENTRY in names:
                # the sha256 manifest subsumes the per-entry CRC check
                # (zf.read CRC-verifies as it streams), so the artifact is
                # decompressed once here, not twice
                manifest = json.loads(zf.read(_CHECKSUMS_ENTRY))
                for name, want in manifest.items():
                    if name not in names:
                        raise CheckpointInvalid(
                            f"{path}: manifest names missing entry {name!r}")
                    got = hashlib.sha256(zf.read(name)).hexdigest()
                    if got != want:
                        raise CheckpointInvalid(
                            f"{path}: sha256 mismatch for {name!r}")
            else:
                # legacy artifact without a manifest: zip CRCs only
                bad = zf.testzip()
                if bad is not None:
                    raise CheckpointInvalid(
                        f"{path}: CRC mismatch in {bad!r}")
    except CheckpointInvalid:
        raise
    except Exception as e:
        # BadZipFile, zlib.error from a corrupt deflate stream, OSError,
        # manifest JSON errors, ... — all mean "not a loadable checkpoint"
        raise CheckpointInvalid(f"{path}: {type(e).__name__}: {e}") from e


def _npz_safe(arrays: Dict[str, np.ndarray]) -> Tuple[Dict[str, np.ndarray],
                                                      Dict[str, str]]:
    """np.savez silently stores extension dtypes (ml_dtypes bfloat16 etc.) as
    raw void bytes; cast them to float32 for storage and record the original
    dtype name in a sidecar so the round-trip preserves dtype."""
    safe, dtype_map = {}, {}
    for k, a in arrays.items():
        if a.dtype.kind == "V":  # ml_dtypes extension types report kind 'V'
            dtype_map[k] = a.dtype.name
            safe[k] = a.astype(np.float32)
        else:
            safe[k] = a
    return safe, dtype_map


def _restore_dtypes(arrays: Dict[str, np.ndarray],
                    dtype_map: Dict[str, str]) -> Dict[str, np.ndarray]:
    if not dtype_map:
        return arrays
    import ml_dtypes
    out = dict(arrays)
    for k, name in dtype_map.items():
        if k in out:
            out[k] = out[k].astype(np.dtype(getattr(ml_dtypes, name)))
    return out


def _flatten(prefix: str, tree: Pytree, out: Dict[str, np.ndarray]) -> None:
    """Flatten a pytree of arrays into path-keyed entries. Supports the
    nested-dict/list/tuple trees the runtime uses; '/' in keys is reserved."""
    if tree is None:
        return
    if isinstance(tree, dict):
        for k, v in tree.items():
            if "/" in str(k):
                raise ValueError(f"'/' not allowed in checkpoint key: {k!r}")
            _flatten(f"{prefix}/{k}", v, out)
    elif isinstance(tree, (list, tuple)):
        tag = "T" if isinstance(tree, tuple) else "L"
        for i, v in enumerate(tree):
            _flatten(f"{prefix}/{tag}{i}", v, out)
    else:
        out[prefix] = np.asarray(tree)


def _unflatten(entries: Dict[str, np.ndarray]) -> Pytree:
    """Rebuild the nested structure from path-keyed arrays."""
    return _materialize(_nest(entries))


def _nest(entries: Dict[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, arr in entries.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def _unflatten_like(template: Pytree, node: Any, path: str = "",
                    strict: bool = True) -> Pytree:
    """Rebuild a pytree with the TEMPLATE's structure from the raw nested
    path-dict. ``_flatten`` stores only leaves, so containers with none
    (param-less layers' ``{}`` params, empty per-layer state dicts) leave no
    trace in the npz — a purely positional rebuild would drop dict keys and,
    worse, silently left-shift list entries. The template (a freshly
    ``init()``-ed net, same config) supplies the true structure; stored
    arrays fill its leaves.

    ``strict=True`` (params): stored keys outside the template are a config
    mismatch. ``strict=False`` (layer state): extra stored keys are kept —
    state dicts legitimately grow at runtime (rnn carries etc.), so the
    init() template is a floor, not the full schema."""
    if isinstance(template, dict):
        node = node if isinstance(node, dict) else {}
        extra = set(node) - {str(k) for k in template}
        if extra and strict:
            raise ValueError(
                f"checkpoint has entries not in the model at '{path}': "
                f"{sorted(extra)} — config mismatch?")
        out = {k: _unflatten_like(tv, node.get(str(k)), f"{path}/{k}", strict)
               for k, tv in template.items()}
        for k in sorted(extra):
            out[k] = _materialize(node[k])
        return out
    if isinstance(template, (list, tuple)):
        node = node if isinstance(node, dict) else {}
        seq = [_unflatten_like(tv, node.get(f"L{i}", node.get(f"T{i}")),
                               f"{path}/{i}", strict)
               for i, tv in enumerate(template)]
        extra_idx = [k for k in node
                     if k[:1] in ("L", "T") and k[1:].isdigit()
                     and int(k[1:]) >= len(template)]
        if extra_idx:
            if strict:
                raise ValueError(
                    f"checkpoint has entries beyond the model's "
                    f"{len(template)} at '{path}': {sorted(extra_idx)} — "
                    "config mismatch?")
            seq.extend(_materialize(node[k]) for k in
                       sorted(extra_idx, key=lambda k: int(k[1:])))
        return tuple(seq) if isinstance(template, tuple) else seq
    if node is None:
        if not strict:
            # lenient (state): a leaf the checkpoint predates keeps its
            # init() value — old checkpoints stay loadable when a layer
            # grows new state
            return template
        raise ValueError(f"checkpoint is missing array for '{path}' — "
                         "config mismatch?")
    return node


def _materialize(node: Any) -> Any:
    if not isinstance(node, dict):
        return node
    keys = list(node.keys())
    # list/tuple nodes were encoded as L0,L1,... / T0,T1,...
    if keys and all(k[:1] in ("L", "T") and k[1:].isdigit() for k in keys):
        tag = keys[0][0]
        items = [(_materialize(node[k]), int(k[1:])) for k in keys]
        items.sort(key=lambda kv: kv[1])
        seq = [v for v, _ in items]
        return tuple(seq) if tag == "T" else seq
    return {k: _materialize(v) for k, v in node.items()}


class ModelSerializer:
    """Static save/restore (parity: ``ModelSerializer``)."""

    @staticmethod
    def write_model(net, path: str, save_updater: bool = True,
                    model_class: Optional[str] = None) -> None:
        """Write network → zip. `net` is a MultiLayerNetwork or
        ComputationGraph (anything with .conf/.params/.state/.updater_state).
        ``model_class`` overrides the recorded class name — used by
        ``util.durable`` when serializing a detached snapshot shim whose
        Python type is not the runtime network class."""
        arrays: Dict[str, np.ndarray] = {}
        params = jax.device_get(net.params)
        _flatten("params", params, arrays)
        _flatten("state", jax.device_get(net.state), arrays)
        if save_updater and net.updater_state is not None:
            _flatten("updater", jax.device_get(net.updater_state), arrays)
        arrays, dtype_map = _npz_safe(arrays)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        training_state = {
            "format_version": _FORMAT_VERSION,
            "model_class": model_class or type(net).__name__,
            "iteration_count": getattr(net, "iteration_count", 0),
            "epoch_count": getattr(net, "epoch_count", 0),
            "update_count": getattr(net, "_update_count", 0),
            "has_updater": bool(save_updater and net.updater_state is not None),
        }
        entries = {_CONFIG_ENTRY: net.conf.to_json().encode("utf-8"),
                   _ARRAYS_ENTRY: buf.getvalue(),
                   _STATE_ENTRY: json.dumps(training_state,
                                            indent=2).encode("utf-8")}
        if dtype_map:
            entries[_DTYPES_ENTRY] = json.dumps(dtype_map,
                                                indent=2).encode("utf-8")
        manifest = {name: hashlib.sha256(data).hexdigest()
                    for name, data in entries.items()}
        # one buffered artifact by design: the whole-blob payload is what
        # lets the "checkpoint.write" fault seam script torn writes
        # deterministically; getbuffer() hands the bytes over without a
        # second copy
        zbuf = io.BytesIO()
        with zipfile.ZipFile(zbuf, "w", zipfile.ZIP_DEFLATED) as zf:
            for name, data in entries.items():
                zf.writestr(name, data)
            zf.writestr(_CHECKSUMS_ENTRY, json.dumps(manifest, indent=2))
        _write_file_atomic(path, zbuf.getbuffer())

    @staticmethod
    def _read(path: str) -> Tuple[str, Dict[str, np.ndarray], dict]:
        with zipfile.ZipFile(path, "r") as zf:
            config_json = zf.read(_CONFIG_ENTRY).decode("utf-8")
            npz = np.load(io.BytesIO(zf.read(_ARRAYS_ENTRY)), allow_pickle=False)
            arrays = {k: npz[k] for k in npz.files}
            training_state = json.loads(zf.read(_STATE_ENTRY).decode("utf-8"))
            if _DTYPES_ENTRY in zf.namelist():
                dtype_map = json.loads(zf.read(_DTYPES_ENTRY).decode("utf-8"))
                arrays = _restore_dtypes(arrays, dtype_map)
        return config_json, arrays, training_state

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        """Rebuild a MultiLayerNetwork: config → init() → overwrite pytrees.
        (parity: ``restoreMultiLayerNetwork`` :158)."""
        from ..nn.multilayer import MultiLayerNetwork
        from ..nn.conf.multi_layer import MultiLayerConfiguration

        config_json, arrays, training_state = ModelSerializer._read(path)
        conf = MultiLayerConfiguration.from_json(config_json)
        net = MultiLayerNetwork(conf)
        net.init()  # builds updater + shapes; overwritten below
        groups: Dict[str, Dict[str, np.ndarray]] = {}
        for k, v in arrays.items():
            head, _, rest = k.partition("/")
            groups.setdefault(head, {})[rest] = v
        net.params = _unflatten_like(net.params, _nest(groups.get("params", {})))
        if "state" in groups:
            net.state = _unflatten_like(net.state, _nest(groups["state"]),
                                        strict=False)
        if load_updater and training_state.get("has_updater"):
            restored = _unflatten(groups.get("updater", {}))
            # preserve the structural template from init() where the updater
            # uses tuples/namedtuples internally
            net.updater_state = _restore_like(net.updater_state, restored)
        net.iteration_count = training_state.get("iteration_count", 0)
        net.epoch_count = training_state.get("epoch_count", 0)
        net._update_count = training_state.get("update_count", 0)
        return net

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        from ..nn.graph_runtime import ComputationGraph
        from ..nn.conf.graph import ComputationGraphConfiguration

        config_json, arrays, training_state = ModelSerializer._read(path)
        conf = ComputationGraphConfiguration.from_json(config_json)
        net = ComputationGraph(conf)
        net.init()
        groups: Dict[str, Dict[str, np.ndarray]] = {}
        for k, v in arrays.items():
            head, _, rest = k.partition("/")
            groups.setdefault(head, {})[rest] = v
        net.params = _unflatten_like(net.params, _nest(groups.get("params", {})))
        if "state" in groups:
            net.state = _unflatten_like(net.state, _nest(groups["state"]),
                                        strict=False)
        if load_updater and training_state.get("has_updater"):
            net.updater_state = _restore_like(
                net.updater_state, _unflatten(groups.get("updater", {})))
        net.iteration_count = training_state.get("iteration_count", 0)
        net.epoch_count = training_state.get("epoch_count", 0)
        net._update_count = training_state.get("update_count", 0)
        return net


def _restore_like(template: Pytree, restored: Pytree) -> Pytree:
    """Pour restored leaf values into the structure of `template` (handles
    updaters whose state uses tuples where the npz round-trip made lists)."""
    t_leaves, t_def = jax.tree_util.tree_flatten(template)
    r_leaves = jax.tree_util.tree_leaves(restored)
    if len(t_leaves) != len(r_leaves):
        raise ValueError(
            f"updater state mismatch: checkpoint has {len(r_leaves)} leaves, "
            f"model expects {len(t_leaves)} — was the config changed?")
    r_leaves = [np.asarray(r).astype(t.dtype) if hasattr(t, "dtype") else r
                for t, r in zip(t_leaves, r_leaves)]
    return jax.tree_util.tree_unflatten(t_def, r_leaves)


def save_model(net, path: str, save_updater: bool = True) -> None:
    ModelSerializer.write_model(net, path, save_updater)


def load_model(path: str, load_updater: bool = True):
    """Auto-detect model class from the artifact."""
    _, _, training_state = ModelSerializer._read(path)
    if training_state.get("model_class") == "ComputationGraph":
        return ModelSerializer.restore_computation_graph(path, load_updater)
    return ModelSerializer.restore_multi_layer_network(path, load_updater)
