"""Deterministic fault injection: script failures into named seams.

The production modules call :func:`check` at their I/O seams (checkpoint
writes, remote stats POSTs, the serving inference call, ...). With no
plan installed that is a near-free no-op. Tests install a
:class:`FaultPlan` that scripts EXACTLY which call at which site fails
and how — the hypothesis-style alternative to sleep-based chaos tests
and to monkeypatching module internals: the seam is part of the module's
contract, so tests survive refactors of everything behind it.

Known sites (grep for ``faults.check``):

- ``"checkpoint.write"``   — serialization writing a model artifact
- ``"storage.post"``       — RemoteUIStatsStorageRouter HTTP round-trip
- ``"serving.infer"``      — the inference server's batched model call
- ``"recovery.restore"``   — checkpoint load during recovery
- ``"training.step"``      — once per dispatched step in the shared fit
  loop (``util.ingest.run_fit_loop``) and the early-stopping trainers,
  BEFORE the dispatch; chaos tests script kills/hangs at exact step
  boundaries here (raise = clean crash, ``os._exit`` hook = hard kill,
  ``os.kill(os.getpid(), SIGTERM)`` hook = preemption signal)

Usage::

    plan = FaultPlan()
    plan.fail("storage.post", times=5, exc=ConnectionError("ui down"))
    plan.fail_at("checkpoint.write", call=2, exc=IOError("disk full"))
    with plan.active():
        ...   # the scripted calls raise; everything else proceeds

A fault may also be a callable hook (e.g. to truncate bytes before
raising — a torn write); it receives the payload the site passed.

Each triggered fault is recorded in ``plan.triggered`` (site, call#) and
``plan.trigger_context`` (site, call, payload, plus seam context from
registered providers — :mod:`deeplearning4j_tpu.util.tracing` stamps the
active span, so tests can assert which span a fault landed in).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Union

Fault = Union[BaseException, Callable[[Any], None]]

_lock = threading.Lock()
_active: Optional["FaultPlan"] = None

# Seam-context providers: callables returning a dict merged into the
# context recorded when a fault triggers. util/tracing.py registers one
# that stamps the active span, so a chaos test can assert WHICH span a
# scripted fault landed in.
_context_providers: list = []


def add_context_provider(fn: Callable[[], dict]) -> None:
    if fn not in _context_providers:
        _context_providers.append(fn)


def seam_context() -> dict:
    """The merged context of all registered providers (empty when none)."""
    ctx: dict = {}
    for fn in list(_context_providers):
        try:
            ctx.update(fn() or {})
        except Exception:
            pass            # a broken provider must never mask the seam
    return ctx


class _Rule:
    __slots__ = ("first", "last", "fault")

    def __init__(self, first: int, last: int, fault: Fault):
        self.first = first          # 1-based call numbers, inclusive
        self.last = last
        self.fault = fault

    def matches(self, call: int) -> bool:
        return self.first <= call <= self.last


class FaultPlan:
    """A deterministic schedule of failures keyed by (site, call number).

    Call numbers are 1-based and counted per site from the moment the
    plan is installed. Thread-safe: sites are hit from server/batcher
    threads.
    """

    def __init__(self):
        self._rules: Dict[str, List[_Rule]] = {}
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.triggered: List[tuple] = []   # (site, call#) audit trail
        # one dict per triggered fault: site, call, payload, plus seam
        # context (e.g. the active tracing span) captured at the hit
        self.trigger_context: List[dict] = []

    # -- scripting --

    def fail(self, site: str, *, times: int = 1,
             exc: Fault = None, after: int = 0) -> "FaultPlan":
        """Fail the next ``times`` calls to ``site`` (skipping the first
        ``after`` calls). ``exc``: exception instance/class to raise, or
        a callable hook invoked with the site payload (it may raise
        itself); defaults to ``InjectedFault``."""
        first = after + 1
        self._rules.setdefault(site, []).append(
            _Rule(first, first + times - 1,
                  exc if exc is not None else InjectedFault(site)))
        return self

    def fail_at(self, site: str, call: int, exc: Fault = None) -> "FaultPlan":
        """Fail exactly the ``call``-th (1-based) call to ``site``."""
        self._rules.setdefault(site, []).append(
            _Rule(call, call,
                  exc if exc is not None else InjectedFault(site)))
        return self

    def always(self, site: str, exc: Fault = None) -> "FaultPlan":
        """Fail every call to ``site`` until the plan is uninstalled."""
        return self.fail(site, times=1 << 30, exc=exc)

    # -- bookkeeping --

    def calls(self, site: str) -> int:
        """How many times ``site`` was hit under this plan."""
        with self._lock:
            return self._counts.get(site, 0)

    def _hit(self, site: str, payload: Any) -> None:
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            rule = next((r for r in self._rules.get(site, ())
                         if r.matches(n)), None)
            if rule is not None:
                self.triggered.append((site, n))
                self.trigger_context.append(
                    {"site": site, "call": n, "payload": payload,
                     **seam_context()})
        if rule is None:
            return
        # scripted faults are significant events by definition: the chaos
        # dump must show the injected failure next to its consequences
        from . import flightrecorder as _flight
        _flight.record("fault_injected", site=site, call=n)
        fault = rule.fault
        if isinstance(fault, BaseException):
            raise fault
        if isinstance(fault, type) and issubclass(fault, BaseException):
            raise fault(f"injected fault at {site} (call {n})")
        fault(payload)          # callable hook; may raise on its own

    # -- installation --

    def install(self) -> None:
        global _active
        with _lock:
            if _active is not None and _active is not self:
                raise RuntimeError("another FaultPlan is already active")
            _active = self

    def uninstall(self) -> None:
        global _active
        with _lock:
            if _active is self:
                _active = None

    def active(self):
        """Context manager: install for the duration of the block."""
        plan = self

        class _Ctx:
            def __enter__(self):
                plan.install()
                return plan

            def __exit__(self, *exc):
                plan.uninstall()
                return False

        return _Ctx()


class InjectedFault(Exception):
    """Default exception for scripted faults."""


def check(site: str, payload: Any = None) -> None:
    """Production seam: no-op unless an installed plan scripted a fault
    for this call of ``site``."""
    plan = _active
    if plan is not None:
        plan._hit(site, payload)


def active_plan() -> Optional[FaultPlan]:
    return _active
