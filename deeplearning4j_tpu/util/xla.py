"""XLA compile-time tuning knobs for the hot train-step programs.

The reference's analog is the cuDNN algo-selection knobs threaded through
``CudnnConvolutionHelper`` (``/root/reference/deeplearning4j-cuda/src/main/
java/org/deeplearning4j/nn/layers/convolution/CudnnConvolutionHelper.java:48``
— algo mode, workspace limits). Here the backend seam is the XLA TPU
compiler: per-program ``compiler_options`` passed to ``jax.jit``.

No options are set by default (measured on ResNet-50 @ v5e: the
latency-hiding scheduler is within noise of the default schedule once
buffers are donated; see PERF.md). Opt in via the ``DL4JTPU_XLA_OPTS`` env
var — comma-separated ``flag=value`` pairs, e.g.
``DL4JTPU_XLA_OPTS=xla_tpu_scoped_vmem_limit_kib=32768``. Set it to the
literal ``off`` to disable all options (including any future defaults).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

_TRAIN_DEFAULTS: Dict[str, str] = {}


def scan_unroll() -> int:
    """lax.scan unroll factor for the K-step train loops (fit_scan /
    fit_repeated). 2 by default — XLA removes inter-iteration carry copies
    between the paired bodies (~1.2 ms/step on ResNet-50 @ v5e); override
    with DL4JTPU_SCAN_UNROLL (8 measured slower, larger only pads compile
    time)."""
    n = int(os.environ.get("DL4JTPU_SCAN_UNROLL", "2"))
    if n < 1:
        raise ValueError(f"DL4JTPU_SCAN_UNROLL must be >= 1, got {n}")
    return n


def train_step_options() -> Optional[Dict[str, str]]:
    """compiler_options dict for train-step jits (None = compiler defaults)."""
    raw = os.environ.get("DL4JTPU_XLA_OPTS", "")
    if raw.strip().lower() == "off":
        return None
    import jax
    if jax.default_backend() != "tpu":
        # TPU flags are rejected by the CPU/GPU compilers (tests run on a
        # virtual CPU mesh) — apply only the user's explicit opts there
        opts = {}
    else:
        opts = dict(_TRAIN_DEFAULTS)
    for pair in raw.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(
                f"DL4JTPU_XLA_OPTS entry {pair!r} is not flag=value")
        k, v = pair.split("=", 1)
        opts[k.strip()] = v.strip()
    return opts or None
