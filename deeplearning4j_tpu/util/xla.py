"""XLA compile-time tuning knobs for the hot train-step programs.

The reference's analog is the cuDNN algo-selection knobs threaded through
``CudnnConvolutionHelper`` (``/root/reference/deeplearning4j-cuda/src/main/
java/org/deeplearning4j/nn/layers/convolution/CudnnConvolutionHelper.java:48``
— algo mode, workspace limits). Here the backend seam is the XLA TPU
compiler: per-program ``compiler_options`` passed to ``jax.jit``.

No options are set by default (measured on ResNet-50 @ v5e: the
latency-hiding scheduler is within noise of the default schedule once
buffers are donated; see PERF.md). Opt in via the ``DL4JTPU_XLA_OPTS`` env
var — comma-separated ``flag=value`` pairs, e.g.
``DL4JTPU_XLA_OPTS=xla_tpu_scoped_vmem_limit_kib=32768``. Set it to the
literal ``off`` to disable all options (including any future defaults).
"""

from __future__ import annotations

import functools
import logging
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

_TRAIN_DEFAULTS: Dict[str, str] = {}


def scan_unroll() -> int:
    """lax.scan unroll factor for the K-step train loops (fit_scan /
    fit_repeated). 2 by default — XLA removes inter-iteration carry copies
    between the paired bodies (~1.2 ms/step on ResNet-50 @ v5e); override
    with DL4JTPU_SCAN_UNROLL (8 measured slower, larger only pads compile
    time)."""
    n = int(os.environ.get("DL4JTPU_SCAN_UNROLL", "2"))
    if n < 1:
        raise ValueError(f"DL4JTPU_SCAN_UNROLL must be >= 1, got {n}")
    return n


def train_step_options() -> Optional[Dict[str, str]]:
    """compiler_options dict for train-step jits (None = compiler defaults)."""
    raw = os.environ.get("DL4JTPU_XLA_OPTS", "")
    if raw.strip().lower() == "off":
        return None
    import jax
    if jax.default_backend() != "tpu":
        # TPU flags are rejected by the CPU/GPU compilers (tests run on a
        # virtual CPU mesh) — apply only the user's explicit opts there
        opts = {}
    else:
        opts = dict(_TRAIN_DEFAULTS)
    for pair in raw.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(
                f"DL4JTPU_XLA_OPTS entry {pair!r} is not flag=value")
        k, v = pair.split("=", 1)
        opts[k.strip()] = v.strip()
    return opts or None


# ----------------------------------------------------------------------
# trace-time routing flags
# ----------------------------------------------------------------------

def trace_env_key() -> str:
    """Cache-key suffix for jitted step functions capturing every env
    flag that is read at TRACE time and baked into the compiled program
    (currently the flash-attention routing flags). The runtimes append it
    to their ``_jit_cache`` keys, so flipping ``DL4JTPU_FLASH_ATTENTION``
    / ``DL4JTPU_FLASH_BWD`` takes effect on the next call — a fresh trace
    under the new routing — without manual jit-cache clearing."""
    return (f"fa={os.environ.get('DL4JTPU_FLASH_ATTENTION', 'auto')}"
            f"|fabwd={os.environ.get('DL4JTPU_FLASH_BWD', 'pallas')}")


def pow2_bucket(n: int, cap: int) -> int:
    """Round ``n`` up to the next power of two, capped at ``cap`` (itself
    a power of two): the shared rule for every trace-ladder axis (the
    decode engine's lane buckets AND its fused block length), so any
    requested size maps into a FIXED, enumerable trace set and
    ``jit_retraces_total`` stays pinned however callers configure it."""
    if n < 1:
        raise ValueError(f"bucketed size must be >= 1, got {n}")
    if cap < 1 or (cap & (cap - 1)):
        raise ValueError(f"cap must be a power of two, got {cap}")
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


def keyed_jit(cache: Dict[str, Any], fn: Callable, *, extra: str = "",
              wrap: Optional[Callable[[Callable], Callable]] = None,
              name: Optional[str] = None, registry=None, **jit_kw):
    """ONE copy of the trace-env-keyed jit-cache lookup the sharded
    trainers use: returns the jit of ``fn`` cached under the CURRENT
    :func:`trace_env_key`, compiling a fresh one when a routing flag has
    flipped since the cached trace (the trainer-side analog of the net
    runtimes' ``_jit_cache`` key suffix).

    ``extra`` extends the key for callers that maintain several traces per
    flag state (e.g. the decode engine's per-bucket step functions);
    ``wrap`` post-processes a freshly built jit exactly once (e.g.
    :func:`retrace_guard`), so the wrapper's own state survives cache
    hits. ``name`` (when ``wrap`` is not given) wraps the fresh jit in a
    :func:`retrace_guard` under that name — retrace counting plus the
    compile-time/cost-analysis metrics — so every keyed trainer step is a
    measured jit site without each caller re-spelling the guard."""
    import jax
    key = trace_env_key() + (f"|{extra}" if extra else "")
    jitted = cache.get(key)
    if jitted is None:
        jitted = jax.jit(fn, **jit_kw)
        if wrap is not None:
            jitted = wrap(jitted)
        elif name is not None:
            jitted = retrace_guard(jitted, name, registry)
        cache[key] = jitted
    return jitted


# ----------------------------------------------------------------------
# compiled-cost metrics: measured FLOPs/bytes + compile wall time
# ----------------------------------------------------------------------

# compile times span ms (tiny eval programs) to minutes (large train
# steps on a real TPU) — the default RPC-latency buckets top out at 10s
_COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0, 60.0, 120.0, 300.0)


def _reg(registry=None):
    from . import metrics as _metrics
    return registry if registry is not None else _metrics.REGISTRY


def compile_seconds_histogram(registry=None):
    return _reg(registry).histogram(
        "xla_compile_seconds",
        "Wall time of each fresh compilation (trace + XLA compile) per "
        "guarded jitted function", ("fn",), buckets=_COMPILE_BUCKETS)


def compiled_flops_gauge(registry=None):
    return _reg(registry).gauge(
        "compiled_flops",
        "HLO cost-analysis FLOPs of the most recently compiled program "
        "per guarded jitted function (measured from the lowered module, "
        "not an analytic formula)", ("fn",))


def compiled_bytes_gauge(registry=None):
    return _reg(registry).gauge(
        "compiled_bytes",
        "HLO cost-analysis bytes accessed of the most recently compiled "
        "program per guarded jitted function", ("fn",))


def cost_analysis_enabled() -> bool:
    """``DL4JTPU_COST_ANALYSIS=0`` skips the per-compile HLO cost
    analysis (the lowering re-walk costs ~0.1s per fresh signature on a
    small transformer — ~4% of its compile time — but a caller compiling
    thousands of tiny programs may want it off)."""
    return os.environ.get("DL4JTPU_COST_ANALYSIS", "1") != "0"


def compiled_costs(fn: Callable, *args, **kwargs) -> Optional[Dict[str, float]]:
    """Measured cost of the program ``fn`` compiles for these arguments:
    ``{"flops": ..., "bytes_accessed": ...}`` from the lowered module's
    HLO cost analysis, or None when unavailable.

    Uses ``Lowered.cost_analysis()`` — NO second backend compile: after
    the jit call itself compiled, re-lowering rides the warm jaxpr cache
    and the analysis walks unoptimized HLO (matmul FLOPs are identical to
    the optimized program's; elementwise counts differ by <1% on the
    models in-tree). Safe after donation: lowering only needs avals,
    never the (possibly consumed) buffers."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        ca = lower(*args, **kwargs).cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    if ca.get("flops"):
        out["flops"] = float(ca["flops"])
    if ca.get("bytes accessed"):
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


# ----------------------------------------------------------------------
# retrace guard
# ----------------------------------------------------------------------

def _abstract_signature(args: tuple, kwargs: dict) -> Tuple:
    """The (shape, dtype) skeleton jit keys its compilation cache on —
    arrays by shape+dtype, python scalars/static args by value, anything
    else by type."""
    import jax

    def leaf_sig(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return ("a", tuple(leaf.shape), str(leaf.dtype))
        if leaf is None or isinstance(leaf, (bool, int, float, str)):
            return ("v", leaf)
        return ("t", type(leaf).__name__)

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(leaf_sig(l) for l in leaves))


def retrace_guard(fn: Callable, name: str, registry=None) -> Callable:
    """Wrap a jitted callable to count compilations into
    ``jit_retraces_total{fn=name}`` and record each fresh compile's
    measured cost.

    Each call computes the abstract signature of its arguments (shape +
    dtype skeleton — the same thing jit keys its cache on); a signature
    never seen by THIS wrapper increments the counter. Steady-state
    training therefore pins the counter at exactly 1 per guarded step
    function, and the no-retrace regression test enforces it on CPU.

    A fresh signature additionally records:

    - ``xla_compile_seconds{fn}`` — wall time of the compiling call
      (trace + XLA compile; dispatch is async, so execution is excluded);
    - ``compiled_flops{fn}`` / ``compiled_bytes{fn}`` — the lowered
      program's HLO cost analysis (:func:`compiled_costs`), the MEASURED
      counterpart of the analytic formulas in bench.py — plus the latest
      analysis on ``wrapped.compiled_costs``;
    - a ``compile`` flight-recorder event (retraces after the first carry
      the differing signature, so a post-mortem dump names the churning
      input).

    ``DL4JTPU_RETRACE_WARN=1`` additionally logs every retrace after the
    first with the differing abstract signature — the fastest way to find
    which input's shape/dtype is churning the compile cache.
    """
    from . import flightrecorder as _flight
    from . import ingest as _ingest
    counter = _ingest.retrace_counter(registry)
    compile_hist = compile_seconds_histogram(registry)
    flops_gauge = compiled_flops_gauge(registry)
    bytes_gauge = compiled_bytes_gauge(registry)
    seen: Dict[Tuple, int] = {}
    last: list = []

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        key = _abstract_signature(args, kwargs)
        if key in seen:
            return fn(*args, **kwargs)
        idx = seen[key] = len(seen)
        counter.inc(fn=name)
        if idx > 0 and os.environ.get("DL4JTPU_RETRACE_WARN") == "1":
            logger.warning(
                "retrace #%d of %s — new abstract signature:\n  now:  "
                "%s\n  prev: %s", idx, name, key[1],
                last[0][1] if last else "?")
        prev = last[0][1] if last else None
        last[:] = [key]
        # the compiling call: trace + compile happen synchronously inside
        # it, execution is dispatched async — so the wall time here IS
        # the compile cost the caller paid
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        compile_hist.observe(dt, fn=name)
        event = {"fn": name, "signature_idx": idx,
                 "compile_seconds": round(dt, 4)}
        costs = (compiled_costs(fn, *args, **kwargs)
                 if cost_analysis_enabled() else None)
        if costs is not None:
            wrapped.compiled_costs = costs
            if "flops" in costs:
                flops_gauge.set(costs["flops"], fn=name)
                event["flops"] = costs["flops"]
            if "bytes_accessed" in costs:
                bytes_gauge.set(costs["bytes_accessed"], fn=name)
        if idx > 0:
            event["signature"] = str(key[1])
            event["prev_signature"] = str(prev)
        _flight.record("compile", **event)
        return out

    wrapped.signatures_seen = seen
    wrapped.compiled_costs = None
    return wrapped
