"""XLA compile-time tuning knobs for the hot train-step programs.

The reference's analog is the cuDNN algo-selection knobs threaded through
``CudnnConvolutionHelper`` (``/root/reference/deeplearning4j-cuda/src/main/
java/org/deeplearning4j/nn/layers/convolution/CudnnConvolutionHelper.java:48``
— algo mode, workspace limits). Here the backend seam is the XLA TPU
compiler: per-program ``compiler_options`` passed to ``jax.jit``.

No options are set by default (measured on ResNet-50 @ v5e: the
latency-hiding scheduler is within noise of the default schedule once
buffers are donated; see PERF.md). Opt in via the ``DL4JTPU_XLA_OPTS`` env
var — comma-separated ``flag=value`` pairs, e.g.
``DL4JTPU_XLA_OPTS=xla_tpu_scoped_vmem_limit_kib=32768``. Set it to the
literal ``off`` to disable all options (including any future defaults).
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

_TRAIN_DEFAULTS: Dict[str, str] = {}


def scan_unroll() -> int:
    """lax.scan unroll factor for the K-step train loops (fit_scan /
    fit_repeated). 2 by default — XLA removes inter-iteration carry copies
    between the paired bodies (~1.2 ms/step on ResNet-50 @ v5e); override
    with DL4JTPU_SCAN_UNROLL (8 measured slower, larger only pads compile
    time)."""
    n = int(os.environ.get("DL4JTPU_SCAN_UNROLL", "2"))
    if n < 1:
        raise ValueError(f"DL4JTPU_SCAN_UNROLL must be >= 1, got {n}")
    return n


def train_step_options() -> Optional[Dict[str, str]]:
    """compiler_options dict for train-step jits (None = compiler defaults)."""
    raw = os.environ.get("DL4JTPU_XLA_OPTS", "")
    if raw.strip().lower() == "off":
        return None
    import jax
    if jax.default_backend() != "tpu":
        # TPU flags are rejected by the CPU/GPU compilers (tests run on a
        # virtual CPU mesh) — apply only the user's explicit opts there
        opts = {}
    else:
        opts = dict(_TRAIN_DEFAULTS)
    for pair in raw.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(
                f"DL4JTPU_XLA_OPTS entry {pair!r} is not flag=value")
        k, v = pair.split("=", 1)
        opts[k.strip()] = v.strip()
    return opts or None


# ----------------------------------------------------------------------
# trace-time routing flags
# ----------------------------------------------------------------------

def trace_env_key() -> str:
    """Cache-key suffix for jitted step functions capturing every env
    flag that is read at TRACE time and baked into the compiled program
    (currently the flash-attention routing flags). The runtimes append it
    to their ``_jit_cache`` keys, so flipping ``DL4JTPU_FLASH_ATTENTION``
    / ``DL4JTPU_FLASH_BWD`` takes effect on the next call — a fresh trace
    under the new routing — without manual jit-cache clearing."""
    return (f"fa={os.environ.get('DL4JTPU_FLASH_ATTENTION', 'auto')}"
            f"|fabwd={os.environ.get('DL4JTPU_FLASH_BWD', 'pallas')}")


def keyed_jit(cache: Dict[str, Any], fn: Callable, *, extra: str = "",
              wrap: Optional[Callable[[Callable], Callable]] = None,
              **jit_kw):
    """ONE copy of the trace-env-keyed jit-cache lookup the sharded
    trainers use: returns the jit of ``fn`` cached under the CURRENT
    :func:`trace_env_key`, compiling a fresh one when a routing flag has
    flipped since the cached trace (the trainer-side analog of the net
    runtimes' ``_jit_cache`` key suffix).

    ``extra`` extends the key for callers that maintain several traces per
    flag state (e.g. the decode engine's per-bucket step functions);
    ``wrap`` post-processes a freshly built jit exactly once (e.g.
    :func:`retrace_guard`), so the wrapper's own state survives cache
    hits."""
    import jax
    key = trace_env_key() + (f"|{extra}" if extra else "")
    jitted = cache.get(key)
    if jitted is None:
        jitted = jax.jit(fn, **jit_kw)
        if wrap is not None:
            jitted = wrap(jitted)
        cache[key] = jitted
    return jitted


# ----------------------------------------------------------------------
# retrace guard
# ----------------------------------------------------------------------

def _abstract_signature(args: tuple, kwargs: dict) -> Tuple:
    """The (shape, dtype) skeleton jit keys its compilation cache on —
    arrays by shape+dtype, python scalars/static args by value, anything
    else by type."""
    import jax

    def leaf_sig(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return ("a", tuple(leaf.shape), str(leaf.dtype))
        if leaf is None or isinstance(leaf, (bool, int, float, str)):
            return ("v", leaf)
        return ("t", type(leaf).__name__)

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(leaf_sig(l) for l in leaves))


def retrace_guard(fn: Callable, name: str, registry=None) -> Callable:
    """Wrap a jitted callable to count compilations into
    ``jit_retraces_total{fn=name}``.

    Each call computes the abstract signature of its arguments (shape +
    dtype skeleton — the same thing jit keys its cache on); a signature
    never seen by THIS wrapper increments the counter. Steady-state
    training therefore pins the counter at exactly 1 per guarded step
    function, and the no-retrace regression test enforces it on CPU.

    ``DL4JTPU_RETRACE_WARN=1`` additionally logs every retrace after the
    first with the differing abstract signature — the fastest way to find
    which input's shape/dtype is churning the compile cache.
    """
    from . import ingest as _ingest
    counter = _ingest.retrace_counter(registry)
    seen: Dict[Tuple, int] = {}
    last: list = []

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        key = _abstract_signature(args, kwargs)
        if key not in seen:
            seen[key] = len(seen)
            counter.inc(fn=name)
            if seen[key] > 0 and os.environ.get("DL4JTPU_RETRACE_WARN") == "1":
                logger.warning(
                    "retrace #%d of %s — new abstract signature:\n  now:  "
                    "%s\n  prev: %s", len(seen) - 1, name, key[1],
                    last[0][1] if last else "?")
            last[:] = [key]
        return fn(*args, **kwargs)

    wrapped.signatures_seen = seen
    return wrapped
