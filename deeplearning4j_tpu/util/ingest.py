"""Host ingest & async dispatch: the pipeline stage between a batch source
and the jitted train step.

Parity: the reference splits this concern across ``AsyncDataSetIterator``
(L4 — ETL/compute overlap via a prefetch thread) and ``ParallelWrapper``
(L6 — dispatch overlap across workers). JAX dispatch is already
asynchronous, so the residual host costs in ``fit()`` are (1) blocking on
``float(loss)`` every step, (2) synchronous ``jax.device_put`` of each
host batch on the consumer thread, and (3) per-step Python dispatch
overhead. This module removes all three without changing training
numerics:

- :class:`LazyScore` — a loss that stays on device until somebody reads
  it. Listeners receive it through ``iteration_done``; ``float(score)``
  (or ``.value()``) performs the device→host sync and counts it into
  ``training_host_syncs_total``, so a listener at ``frequency=N`` costs
  exactly one sync per N steps and a listener that never reads the score
  costs zero.
- :class:`InflightWindow` — bounds how many dispatched steps may be in
  flight (default 2, ``DL4JTPU_MAX_INFLIGHT``). Blocking waits on the
  OLDEST step's completion (``block_until_ready``), which is a device
  fence, not a host transfer — the loss value never moves to the host.
- :func:`stage` — wraps any (x, y, mask) batch iterable with a
  background thread that ``jax.device_put``s each batch and blocks until
  the transfer lands, so the queue holds HBM-resident batches and the
  h2d DMA overlaps the previous step's compute. This is applied to every
  ``fit(iterator)`` call by default (``DL4JTPU_INGEST=0`` disables).
- :func:`coalesced` — opportunistically groups runs of K consecutive
  same-shape maskless batches for a single ``fit_scan`` dispatch.
  Off by default (the fused path derives per-step rng differently, so
  flipping it silently would change training draws); enable with
  ``DL4JTPU_COALESCE_K`` or ``fit(..., coalesce=K)``.

Observability (all into the PR-2 metrics registry): prefetch queue depth
gauge, h2d bytes/seconds counters, staged-batch counts, a
host-gap-between-dispatches histogram recorded by the fit loops, and
optional per-batch ingest spans when a tracer is attached.
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import threading
import time
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from . import faults as _faults
from . import flightrecorder as _flight
from . import metrics as _metrics

logger = logging.getLogger("deeplearning4j_tpu")


# ----------------------------------------------------------------------
# the shared producer/queue core (also backs the async dataset iterators)
# ----------------------------------------------------------------------

class ProducerQueue:
    """Bounded queue + stop-flag poison + sentinel + fail-fast error
    hand-off: the one copy of the producer/consumer machinery shared by
    :func:`stage` and ``datasets.iterator.AsyncDataSetIterator``.

    Producer side: ``put`` (gives up promptly once ``stop`` is set — the
    reset/close poison), ``fail(exc)`` then ``finish()`` in a finally.
    Consumer side: ``get`` returns the next item or ``SENTINEL``; pending
    producer errors raise as soon as they are observed, BEFORE any
    queued item is handed out. ``drain_and_join`` discards staged items
    (unblocking a producer stuck on a full queue) and reports whether
    the producer thread actually exited.
    """

    SENTINEL = object()

    def __init__(self, maxsize: int):
        self.queue: "queue.Queue" = queue.Queue(maxsize=max(1, maxsize))
        self.stop = threading.Event()
        self.error: Optional[BaseException] = None

    # -- producer side -------------------------------------------------

    def put(self, item, timeout: float = 0.05) -> bool:
        while not self.stop.is_set():
            try:
                self.queue.put(item, timeout=timeout)
                return True
            except queue.Full:
                continue
        return False

    def fail(self, exc: BaseException) -> None:
        self.error = exc

    def finish(self) -> None:
        self.put(self.SENTINEL)

    # -- consumer side -------------------------------------------------

    def raise_pending(self) -> None:
        if self.error is not None:
            err, self.error = self.error, None
            raise err

    def get(self, timeout: float = 0.05):
        """Next item or ``SENTINEL``. Fail fast: a producer error raises
        at the first observation, even with items still queued — and a
        sentinel re-checks, so an error set right before ``finish()``
        cannot slip out as a clean end-of-stream."""
        while True:
            self.raise_pending()
            try:
                item = self.queue.get(timeout=timeout)
            except queue.Empty:
                continue
            if item is self.SENTINEL:
                self.raise_pending()
            return item

    def drain_and_join(self, thread: threading.Thread,
                       join_timeout: float = 5.0) -> bool:
        """Poison the producer, discard staged items, wait for the thread.
        Returns False if the thread is still alive (stuck inside the
        source) — callers that would restart over the same source must
        treat that as an error, not race a second producer against it."""
        self.stop.set()
        try:
            while True:
                self.queue.get_nowait()
        except queue.Empty:
            pass
        thread.join(timeout=join_timeout)
        return not thread.is_alive()


# ----------------------------------------------------------------------
# knobs
# ----------------------------------------------------------------------

def max_inflight_default() -> int:
    """Bounded dispatch window for fit() (``DL4JTPU_MAX_INFLIGHT``, default
    2: the current step computes while the next one stages + dispatches)."""
    n = int(os.environ.get("DL4JTPU_MAX_INFLIGHT", "2"))
    if n < 1:
        raise ValueError(f"DL4JTPU_MAX_INFLIGHT must be >= 1, got {n}")
    return n


def staging_enabled() -> bool:
    return os.environ.get("DL4JTPU_INGEST", "1") != "0"


def coalesce_k_default() -> int:
    """Run length for same-shape batch coalescing (0/1 = off)."""
    return int(os.environ.get("DL4JTPU_COALESCE_K", "0"))


def already_staged(data) -> bool:
    """True when the source already ships device-resident batches (an
    AsyncDataSetIterator constructed with ``device_put=True``) — wrapping
    it again would only add a queue hop."""
    return bool(getattr(data, "device_put", False))


# ----------------------------------------------------------------------
# metric families (get-or-create: idempotent across pipelines)
# ----------------------------------------------------------------------

_GAP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 1.0)


def _reg(registry=None) -> _metrics.MetricsRegistry:
    return registry if registry is not None else _metrics.REGISTRY


def sync_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "training_host_syncs_total",
        "Device->host loss transfers forced by score readers")


def retrace_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "jit_retraces_total",
        "Distinct abstract signatures (= compilations) seen per guarded "
        "jitted function", ("fn",))


def _queue_gauge(registry=None) -> _metrics.Gauge:
    return _reg(registry).gauge(
        "ingest_queue_depth", "Staged batches waiting in the prefetch queue",
        ("stage",))


def _h2d_bytes(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "ingest_h2d_bytes_total", "Host bytes shipped to device by ingest",
        ("stage",))


def _h2d_seconds(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "ingest_h2d_seconds_total",
        "Producer-thread seconds spent staging (device_put + transfer wait)",
        ("stage",))


def _staged_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "ingest_batches_staged_total", "Batches staged by ingest", ("stage",))


def host_gap_histogram(registry=None) -> _metrics.Histogram:
    return _reg(registry).histogram(
        "fit_host_gap_seconds",
        "Host time between consecutive step dispatches in fit() (batch "
        "fetch + listener work; device compute excluded)", ("model",),
        buckets=_GAP_BUCKETS)


def records_read_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "pipeline_records_read_total",
        "Records decoded from shard files by the record input pipeline "
        "(data.pipeline)", ("stage",))


def records_skipped_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "pipeline_records_skipped_total",
        "Corrupt records dropped by the skip-with-counter policy — any "
        "nonzero value on a production run means a shard needs fsck",
        ("stage",))


def augment_seconds_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "pipeline_augment_seconds_total",
        "Producer-side seconds spent in the jitted augmentation stage "
        "(host dispatch wall — the device compute overlaps the step)",
        ("stage",))


def pipeline_batches_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "pipeline_batches_total",
        "Batches assembled by the record input pipeline", ("stage",))


def measured_flops_gauge(registry=None) -> _metrics.Gauge:
    return _reg(registry).gauge(
        "measured_flops_per_sec",
        "Live training FLOP/s: the compiled train step's HLO "
        "cost-analysis FLOPs (compiled_flops) over wall time between "
        "dispatches — measured, not analytic", ("model",))


def measured_mfu_gauge(registry=None) -> _metrics.Gauge:
    return _reg(registry).gauge(
        "measured_mfu",
        "Live model FLOPs utilization: measured_flops_per_sec over the "
        "attached chip's published bf16 peak (series absent when the "
        "device kind has no known peak — CPU runs read "
        "measured_flops_per_sec instead)", ("model",))


class _MfuMeter:
    """Live measured-performance gauges for :func:`run_fit_loop`.

    Combines the guarded train step's cost-analysis FLOPs
    (``compiled_flops{fn}``, recorded by ``util.xla.retrace_guard`` at
    compile time) with wall time between dispatches into
    ``measured_flops_per_sec{model}`` and — when the chip's peak is known
    — ``measured_mfu{model}``. The first dispatch (the compiling one)
    only anchors the clock: its wall time is compile, not compute.
    Unknown peaks (CPU) degrade to the flops/sec gauge; an unguarded step
    override (no compiled_flops series) records nothing.
    """

    def __init__(self, model_label: str, registry=None):
        from . import profiling as _profiling
        from . import xla as _xla
        self.model_label = model_label
        self._flops = _xla.compiled_flops_gauge(registry)
        self._rate = measured_flops_gauge(registry)
        self._mfu = measured_mfu_gauge(registry)
        try:
            self._peak = _profiling.peak_flops_per_sec()
        except Exception:
            self._peak = None
        self._t0: Optional[float] = None
        self._total = 0.0

    def on_dispatch(self, kind: str) -> None:
        fn = (f"{self.model_label}.train_scan" if kind == "scan"
              else f"{self.model_label}.train_step")
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
            return
        flops = self._flops.value(fn=fn)
        if not flops:
            return
        self._total += flops
        elapsed = now - self._t0
        if elapsed <= 0:
            return
        rate = self._total / elapsed
        self._rate.set(rate, model=self.model_label)
        if self._peak:
            self._mfu.set(rate / self._peak, model=self.model_label)


# ----------------------------------------------------------------------
# LazyScore
# ----------------------------------------------------------------------

class LazyScore:
    """A training loss that stays on device until read.

    ``float(score)`` / ``score.value()`` transfers it to the host (once;
    the result is cached) and increments ``training_host_syncs_total``.
    Listeners that gate on ``iteration % frequency`` therefore pay one
    sync per window; listeners that never read the score pay none.
    """

    __slots__ = ("_device", "_host", "_registry")

    def __init__(self, device_value, registry=None):
        self._device = device_value
        self._host: Optional[float] = None
        self._registry = registry

    @property
    def resolved(self) -> bool:
        return self._host is not None

    def value(self) -> float:
        if self._host is None:
            sync_counter(self._registry).inc()
            v, self._device = self._device, None
            self._host = float(v)
        return self._host

    def __float__(self) -> float:
        return self.value()

    def __repr__(self) -> str:
        return (f"LazyScore({self._host})" if self.resolved
                else "LazyScore(<on device>)")


def as_listener_score(loss, registry=None):
    """Wrap a device loss for listener delivery; host scalars (the
    fit_scan/fit_repeated replay path, which already paid one bulk
    transfer for all K losses) pass through untouched."""
    if isinstance(loss, (int, float, np.floating, np.integer)):
        return loss
    return LazyScore(loss, registry)


# ----------------------------------------------------------------------
# InflightWindow
# ----------------------------------------------------------------------

class InflightWindow:
    """Bound the number of dispatched-but-unfinished train steps.

    ``push`` records one step's output (any array pytree leaf works; the
    loss is the natural token). Once more than ``max_inflight`` steps are
    outstanding, the oldest is waited on with ``block_until_ready`` — a
    completion fence that keeps the dispatch queue short without ever
    transferring the value to the host.
    """

    def __init__(self, max_inflight: Optional[int] = None):
        self.max_inflight = (max_inflight_default() if max_inflight is None
                             else max(1, int(max_inflight)))
        self._pending: collections.deque = collections.deque()

    def push(self, token) -> None:
        self._pending.append(token)
        while len(self._pending) > self.max_inflight:
            oldest = self._pending.popleft()
            if hasattr(oldest, "block_until_ready"):
                oldest.block_until_ready()

    def drain(self) -> None:
        while self._pending:
            oldest = self._pending.popleft()
            if hasattr(oldest, "block_until_ready"):
                oldest.block_until_ready()


# ----------------------------------------------------------------------
# background device staging
# ----------------------------------------------------------------------

class _StagedStream:
    """Iterator over device-staged batches produced by a background thread.

    The producer pulls (x, y, mask)-style tuples from ``source``,
    ``jax.device_put``s every array element (descending into lists, so
    MultiDataSet-style multi-input batches stage too), BLOCKS until the
    transfer completes (so queued batches are HBM-resident, and the DMA
    overlaps the consumer's current step), and enqueues. Errors from the
    source surface on the consumer as soon as they are observed.
    ``close()`` (also called on exhaustion/GC) stops the producer
    promptly.
    """

    def __init__(self, source: Iterable[Tuple], *, stage_name: str,
                 device=None, device_put: bool = True, queue_size: int = 2,
                 registry=None, tracer=None):
        self.stage_name = stage_name
        self.device = device
        self.device_put = device_put
        self.registry = registry
        self.tracer = tracer
        self._source = source
        self._pq = ProducerQueue(queue_size)
        self._finished = False
        self._depth = _queue_gauge(registry)
        self._depth.set_function(self._pq.queue.qsize, stage=stage_name)
        self._bytes = _h2d_bytes(registry)
        self._seconds = _h2d_seconds(registry)
        self._staged = _staged_counter(registry)
        self._thread = threading.Thread(
            target=self._producer, name=f"ingest-{stage_name}", daemon=True)
        self._thread.start()

    # -- producer side -------------------------------------------------

    def _stage_one(self, batch: Tuple) -> Tuple:
        import jax
        span = (self.tracer.start("ingest.stage",
                                  attributes={"stage": self.stage_name})
                if self.tracer is not None else None)
        t0 = time.perf_counter()
        host_bytes = 0

        def put_el(el):
            nonlocal host_bytes
            if isinstance(el, (list, tuple)):   # multi-input/-output batch
                return type(el)(put_el(e) for e in el)
            if el is None or not hasattr(el, "shape"):
                return el
            if not isinstance(el, jax.Array):
                host_bytes += int(getattr(el, "nbytes", 0))
            return jax.device_put(el, self.device)

        staged = tuple(put_el(el) for el in batch)
        # wait for the DMA here, on the producer thread — that wait IS the
        # overlap with the consumer's in-flight step
        for leaf in jax.tree_util.tree_leaves(staged):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
        dt = time.perf_counter() - t0
        self._seconds.inc(dt, stage=self.stage_name)
        if host_bytes:
            self._bytes.inc(host_bytes, stage=self.stage_name)
        self._staged.inc(stage=self.stage_name)
        if span is not None:
            span.attributes["bytes"] = host_bytes
            span.end()
        return staged

    def _producer(self) -> None:
        try:
            for batch in self._source:
                if self._pq.stop.is_set():
                    return
                if self.device_put:
                    batch = self._stage_one(batch)
                else:
                    self._staged.inc(stage=self.stage_name)
                if not self._pq.put(batch):
                    return
        except BaseException as e:   # surfaced on the consumer side
            self._pq.fail(e)
        finally:
            self._pq.finish()

    # -- consumer side -------------------------------------------------

    def __iter__(self) -> Iterator[Tuple]:
        return self

    def __next__(self) -> Tuple:
        if self._finished:
            raise StopIteration
        try:
            item = self._pq.get()
        except BaseException:
            self._finished = True
            raise
        if item is ProducerQueue.SENTINEL:
            self._finished = True
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer (bounded by one in-flight batch) and release
        the queue. Best effort: nothing restarts over this source, so a
        producer stuck inside it is left to die with the process."""
        self._pq.drain_and_join(self._thread)
        self._finished = True

    def __del__(self):
        try:
            self._pq.stop.set()
        except Exception:
            pass


def stage(source: Iterable[Tuple], *, stage_name: str = "fit", device=None,
          device_put: bool = True, queue_size: int = 2, registry=None,
          tracer=None) -> _StagedStream:
    """Wrap a batch iterable with background device staging (double-
    buffered by default: one batch staging while one waits).

    ``device_put=False`` keeps batches on host and only overlaps the
    source's own batch-preparation work — the right mode for sharded
    trainers that place inputs with their own shardings.
    """
    return _StagedStream(source, stage_name=stage_name, device=device,
                         device_put=device_put, queue_size=queue_size,
                         registry=registry, tracer=tracer)


# ----------------------------------------------------------------------
# same-shape coalescing
# ----------------------------------------------------------------------

def _batch_sig(x, y) -> Optional[Tuple]:
    if not (hasattr(x, "shape") and hasattr(y, "shape")):
        return None
    return (tuple(x.shape), str(getattr(x, "dtype", "?")),
            tuple(y.shape), str(getattr(y, "dtype", "?")))


def coalesced(batches: Iterable[Tuple], k: int) -> Iterator[Tuple[str, Tuple]]:
    """Group runs of K consecutive same-shape maskless batches.

    Yields ``("scan", (xs, ys))`` with ``xs``/``ys`` stacked along a new
    leading axis for exactly-K runs, and ``("step", (x, y, mask))`` for
    everything else (masked batches, shape changes, sub-K tails — tails
    run as single steps rather than compiling a second scan length).
    Multi-input graph batches (lists of arrays) are never coalesced.
    """
    if k < 2:
        for b in batches:
            yield ("step", b)
        return
    import jax.numpy as jnp
    buf: list = []
    sig = None

    def _flush():
        for x, y in buf:
            yield ("step", (x, y, None))
        buf.clear()

    for b in batches:
        x, y, m = b[0], b[1], (b[2] if len(b) > 2 else None)
        s = _batch_sig(x, y) if m is None else None
        if s is None:
            yield from _flush()
            sig = None
            yield ("step", b)
            continue
        if buf and s != sig:
            yield from _flush()
        sig = s
        buf.append((x, y))
        if len(buf) == k:
            xs = jnp.stack([x for x, _ in buf])
            ys = jnp.stack([y for _, y in buf])
            buf.clear()
            yield ("scan", (xs, ys))
    yield from _flush()


# ----------------------------------------------------------------------
# the shared async fit loop (MultiLayerNetwork + ComputationGraph)
# ----------------------------------------------------------------------

def run_fit_loop(net, data, labels, mask, epochs: int,
                 coalesce: Optional[int], *, model_label: str,
                 session=None) -> None:
    """The dispatch-asynchronous epoch loop behind both runtimes' ``fit``.

    Per epoch: lazily reset the source (at epoch START, so the final
    epoch never restarts a producer whose work would be discarded), wrap
    iterator sources in background device staging, then dispatch steps
    behind an :class:`InflightWindow`, recording the host gap between
    consecutive dispatches. Coalescing (``k >= 2``) routes exact-K
    same-shape runs through ``fit_scan``; with listeners attached it
    stays off unless the caller passed ``coalesce`` explicitly (listeners
    get replayed host scores there, i.e. per-step host values).

    Every dispatched step first passes the ``"training.step"`` fault seam
    (chaos tests script kills/hangs at exact step boundaries). With a
    ``session`` (``util.durable.DurableSession``) attached, the loop also
    taps the batch stream for data-source cursors (BEFORE staging, so
    cursors are recorded in production order), reports each step for
    checkpointing/watchdog petting, and — when the session asks to stop
    (preemption, max_steps) — drains the in-flight window and returns
    cleanly WITHOUT counting the partial epoch.

    Observability riders: every dispatched step lands a ``train_step``
    flight-recorder event (the black box a watchdog/preemption dump
    replays); a :class:`_MfuMeter` keeps ``measured_mfu{model}`` /
    ``measured_flops_per_sec{model}`` live from the compiled step's
    cost-analysis FLOPs; and ``DL4JTPU_PROFILE_STEPS=start:stop[:dir]``
    brackets exactly that dispatch range (0-based, stop-exclusive,
    counted across epochs within this call) with a ``jax.profiler``
    capture — the in-flight window is drained before the profiler stops,
    so the bracketed steps' device work lands inside the trace.
    """
    single = (labels is not None or hasattr(data, "shape")
              or hasattr(data, "features")
              or (isinstance(data, tuple) and len(data) in (2, 3)))
    k = coalesce_k_default() if coalesce is None else int(coalesce)
    if net.listeners and coalesce is None and k >= 2:
        # listeners demand per-step host-value semantics; the env opt-in
        # alone does not override them — say so instead of silently
        # benchmarking without fusion
        logger.info(
            "DL4JTPU_COALESCE_K=%d ignored: %d listener(s) attached — "
            "pass fit(..., coalesce=%d) to fuse anyway (listeners then "
            "get replayed host scores)", k, len(net.listeners), k)
        k = 0
    elif net.listeners and coalesce is None:
        k = 0
    from . import profiling as _profiling
    gap_hist = host_gap_histogram()
    meter = _MfuMeter(model_label)
    profile_range = _profiling.profile_steps_env()
    capture = (_profiling.StepCapture(profile_range[2])
               if profile_range is not None else None)
    dispatch_idx = 0
    # a session resuming a mid-epoch cursor must not "revive" the source
    # on its first epoch: a cursor at the exact end of the data means
    # zero batches remain, not restart-from-scratch
    revive_ok = not (session is not None
                     and getattr(session, "resuming", False))
    window = None
    try:
        for epoch in range(epochs):
            if hasattr(data, "reset") and (
                    epoch > 0 or (revive_ok and hasattr(data, "has_next")
                                  and not data.has_next())):
                data.reset()
            for l in net.listeners:
                l.on_epoch_start(net, net.epoch_count)
            window = InflightWindow()
            source = net._as_batches(data, labels, mask)
            if session is not None:
                source = session.tap(source, data)
            staged = None
            if not single and staging_enabled() and not already_staged(data):
                staged = stage(source, stage_name="fit",
                               tracer=getattr(net, "ingest_tracer", None))
                source = staged
            n_batches = 0
            t_prev = None
            stopped = False
            try:
                for kind, payload in coalesced(source, k):
                    t_now = time.perf_counter()
                    if t_prev is not None:
                        gap_hist.observe(t_now - t_prev, model=model_label)
                    if (capture is not None and not capture.active
                            and dispatch_idx == profile_range[0]):
                        capture.start()
                    _flight.record(
                        "train_step", model=model_label,
                        epoch=net.epoch_count,
                        iteration=net.iteration_count, dispatch=kind,
                        host_gap_s=(round(t_now - t_prev, 6)
                                    if t_prev is not None else None))
                    _faults.check("training.step", {
                        "model": model_label, "epoch": net.epoch_count,
                        "iteration": net.iteration_count, "kind": kind})
                    if kind == "scan":
                        xs, ys = payload
                        window.push(net.fit_scan(xs, ys))
                        consumed = int(xs.shape[0])
                    else:
                        window.push(net.fit_batch(*payload))
                        consumed = 1
                    meter.on_dispatch(kind)
                    dispatch_idx += 1
                    if (capture is not None and capture.active
                            and dispatch_idx >= profile_range[1]):
                        # the bracketed steps' device work must land
                        # inside the capture, not after it
                        window.drain()
                        capture.stop()
                    n_batches += consumed
                    if session is not None and not session.on_step(
                            net, consumed):
                        # clean stop (preemption / max_steps): every
                        # dispatched step must land before the caller
                        # checkpoints the stop instant
                        window.drain()
                        stopped = True
                        break
                    t_prev = time.perf_counter()
            finally:
                if staged is not None:
                    staged.close()
            if stopped:
                return      # partial epoch: no epoch_end, no count bump
            if n_batches == 0 and epoch > 0:
                raise ValueError(
                    f"epoch {epoch} yielded no batches — the data "
                    "iterator is exhausted and has no reset(); pass a "
                    "resettable iterator (e.g. "
                    "datasets.ListDataSetIterator) when epochs > 1")
            for l in net.listeners:
                l.on_epoch_end(net, net.epoch_count)
            net.epoch_count += 1
            if session is not None:
                session.on_epoch_boundary(net)
    finally:
        if capture is not None and capture.active:
            # same contract as the in-loop stop: the bracketed steps'
            # device work must land inside the trace, even when the fit
            # ran out of batches (or raised) before reaching `stop`
            if window is not None:
                try:
                    window.drain()
                except Exception:
                    pass    # a failed dispatch still ends the capture
            capture.stop()
