"""Resilience substrate: retry/backoff, circuit breaking, deadlines.

Parity-plus: the reference delegates fault tolerance entirely to Spark
task retry (SURVEY §5 — nothing bespoke in-tree). This reproduction owns
serving, remote stats, checkpointing and multi-step training loops, so it
owns ONE composable fault story instead of per-module ad-hoc loops:

- :class:`RetryPolicy` — bounded attempts with exponential backoff and an
  overall deadline.
- :class:`CircuitBreaker` — consecutive failures trip OPEN; after a
  cool-down one HALF_OPEN probe decides between CLOSED and re-OPEN, so an
  unreachable dependency is probed, not hammered.
- :class:`Deadline` — an absolute time budget threaded through queues and
  request handlers.
- :class:`NonFiniteGuard` — host-side budget for skipped non-finite
  training steps (the trainers select old params on-device; this decides
  when skipping becomes raising).

Everything takes an injectable :class:`Clock`, so every failure path is
driven deterministically from tests (``ManualClock`` — no real sleeps),
in the spirit of hypothesis-style deterministic fault injection; see
:mod:`deeplearning4j_tpu.util.faults` for the companion injection
harness.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Callable, Dict, Iterator, Optional, Tuple, Type

logger = logging.getLogger("deeplearning4j_tpu")

# Every constructed CircuitBreaker registers here (weakly), so diagnostic
# dumps — chiefly util.durable.StepWatchdog's no-progress report — can
# name each live breaker's current state without threading references.
_live_breakers: "weakref.WeakSet" = weakref.WeakSet()


def breaker_states() -> Dict[str, str]:
    """Name → state of every live :class:`CircuitBreaker` in the process."""
    return {b.name: b.state for b in sorted(
        list(_live_breakers), key=lambda b: b.name)}


class ResilienceError(Exception):
    """Base class for failures raised by the resilience substrate."""


class RetriesExhausted(ResilienceError):
    """A RetryPolicy ran out of attempts/deadline. ``__cause__`` holds the
    last underlying error."""


class CircuitOpenError(ResilienceError):
    """The call was refused because the circuit breaker is OPEN."""

    def __init__(self, msg: str, retry_after: float = 0.0):
        super().__init__(msg)
        self.retry_after = float(retry_after)


class DeadlineExceeded(ResilienceError):
    """A Deadline expired before the work completed."""


class Clock:
    """Injectable time source. The default reads the monotonic clock and
    really sleeps; tests substitute :class:`ManualClock`."""

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class ManualClock(Clock):
    """Deterministic clock for tests: ``sleep`` advances virtual time
    instantly and records the requested durations."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)
        self.sleeps: list = []

    def monotonic(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(float(seconds))
        self.now += max(0.0, float(seconds))

    def advance(self, seconds: float) -> None:
        self.now += float(seconds)


SYSTEM_CLOCK = Clock()


class Deadline:
    """An absolute point in (clock) time a unit of work must finish by."""

    def __init__(self, budget_s: Optional[float], clock: Clock = SYSTEM_CLOCK):
        self.clock = clock
        self._at = (None if budget_s is None
                    else clock.monotonic() + float(budget_s))

    def remaining(self) -> Optional[float]:
        """Seconds left (None = unbounded); never negative."""
        if self._at is None:
            return None
        return max(0.0, self._at - self.clock.monotonic())

    @property
    def expired(self) -> bool:
        return self._at is not None and self.clock.monotonic() >= self._at

    def check(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(f"{what} exceeded its deadline")


def wait_until(predicate: Callable[[], bool], *,
               timeout_s: Optional[float] = None, poll_s: float = 0.02,
               clock: Clock = SYSTEM_CLOCK,
               desc: str = "condition",
               on_poll: Optional[Callable[[], None]] = None) -> bool:
    """Deadline-bounded polling wait: True as soon as ``predicate()`` is
    truthy, False once ``timeout_s`` elapses (None = wait forever). The
    replacement for fixed test sleeps — a passing wait returns at the
    first poll instead of sleeping the worst case, and a hung condition
    fails at the deadline instead of hanging the suite. ``on_poll`` runs
    every iteration (pet a watchdog, publish a heartbeat)."""
    deadline = Deadline(timeout_s, clock)
    while True:
        if predicate():
            return True
        if deadline.expired:
            logger.warning("wait_until(%s) expired after %.1fs", desc,
                           float(timeout_s or 0))
            return False
        if on_poll is not None:
            on_poll()
        clock.sleep(poll_s)


class RetryPolicy:
    """Exponential-backoff retry with bounded attempts and a total
    deadline.

    ``call(fn)`` runs ``fn`` up to ``max_attempts`` times, sleeping
    ``initial_backoff * multiplier**k`` (capped at ``max_backoff``)
    between attempts via the injected clock. A ``deadline_s`` bounds the
    WHOLE retry loop: no retry is begun (nor slept toward) past it.
    Raises :class:`RetriesExhausted` chaining the last error.
    """

    def __init__(self, *, max_attempts: int = 3,
                 initial_backoff: float = 0.1, max_backoff: float = 10.0,
                 multiplier: float = 2.0,
                 deadline_s: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 clock: Clock = SYSTEM_CLOCK,
                 name: str = "retry", registry=None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff = float(initial_backoff)
        self.max_backoff = float(max_backoff)
        self.multiplier = float(multiplier)
        self.deadline_s = deadline_s
        self.retry_on = retry_on
        self.clock = clock
        self.name = name
        # attempt / give-up counters, labeled by policy name so one
        # scrape separates "remote UI flapping" from "checkpoint flapping"
        from . import metrics as _metrics
        reg = registry if registry is not None else _metrics.REGISTRY
        self._attempts_counter = reg.counter(
            "retry_attempts_total", "Attempts started under a RetryPolicy",
            ("policy",))
        self._give_ups_counter = reg.counter(
            "retry_give_ups_total",
            "Retry loops that exhausted attempts or deadline", ("policy",))

    def backoff(self, attempt: int) -> float:
        """Sleep before attempt ``attempt`` (0-based; attempt 0 has none)."""
        if attempt <= 0:
            return 0.0
        return min(self.max_backoff,
                   self.initial_backoff * self.multiplier ** (attempt - 1))

    def attempts(self) -> Iterator[int]:
        """Yield attempt indices, sleeping the backoff between them and
        stopping early when the policy deadline runs out."""
        deadline = Deadline(self.deadline_s, self.clock)
        for attempt in range(self.max_attempts):
            if attempt > 0:
                wait = self.backoff(attempt)
                rem = deadline.remaining()
                if rem is not None and wait >= rem:
                    # the backoff alone would eat the rest of the deadline
                    # — give up now instead of sleeping toward nothing
                    return
                self.clock.sleep(wait)
            self._attempts_counter.inc(policy=self.name)
            yield attempt

    def record_give_up(self) -> None:
        """Count one exhausted retry loop. ``call()`` does this itself;
        callers driving ``attempts()`` by hand (e.g. the remote stats
        router) call it when their loop ends without success."""
        self._give_ups_counter.inc(policy=self.name)

    def call(self, fn: Callable, *args, **kwargs):
        last: Optional[BaseException] = None
        ran = 0
        for _attempt in self.attempts():
            ran += 1
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last = e
        self.record_give_up()
        cut = ("" if ran == self.max_attempts
               else f" (deadline cut the loop short of {self.max_attempts})")
        raise RetriesExhausted(
            f"{getattr(fn, '__name__', fn)!r} failed after {ran} "
            f"attempts{cut}") from last


# breaker states
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Trip OPEN after ``failure_threshold`` consecutive failures; refuse
    calls while OPEN; after ``reset_timeout_s`` allow ONE probe
    (HALF_OPEN) — its success closes the circuit, its failure re-opens it
    for another cool-down. Thread-safe; clock-injectable.
    """

    def __init__(self, *, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Clock = SYSTEM_CLOCK, name: str = "breaker",
                 on_transition: Optional[Callable[[str, str, str],
                                                  None]] = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.clock = clock
        self.name = name
        # observer fired as (breaker_name, old_state, new_state) on EVERY
        # state change, outside the breaker lock (a hook may read state)
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._pending_transitions: list = []
        self.trips = 0          # times the breaker went CLOSED/HALF_OPEN→OPEN
        self.rejected = 0       # calls refused while OPEN
        _live_breakers.add(self)

    def _set_state(self, new: str) -> None:
        """Must hold self._lock; queues the transition for hooks."""
        if new != self._state:
            self._pending_transitions.append((self._state, new))
        self._state = new

    def _fire_transitions(self) -> None:
        """Must NOT hold self._lock. Hook failures are logged, never
        raised — telemetry must not take down the breaker's caller (the
        serving batcher thread calls this from its failure path)."""
        with self._lock:
            pending, self._pending_transitions = (
                self._pending_transitions, [])
        hook = self.on_transition
        for old, new in pending:
            # every transition lands in the process flight recorder (a
            # breaker flapping open right before a stall is exactly what
            # a post-mortem dump must show), independent of any hook
            from . import flightrecorder as _flight
            _flight.record("breaker_transition", breaker=self.name,
                           from_state=old, to_state=new)
            if hook is not None:
                try:
                    hook(self.name, old, new)
                except Exception:
                    logger.exception(
                        "circuit %s on_transition hook failed (%s -> %s)",
                        self.name, old, new)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            out = self._state
        self._fire_transitions()
        return out

    def _maybe_half_open(self) -> None:
        if (self._state == OPEN
                and self.clock.monotonic() - self._opened_at
                >= self.reset_timeout_s):
            self._set_state(HALF_OPEN)
            self._probe_inflight = False

    def retry_after(self) -> float:
        """Seconds until the next probe is allowed (0 when not OPEN)."""
        with self._lock:
            self._maybe_half_open()
            if self._state != OPEN:
                out = 0.0
            else:
                out = max(0.0, self._opened_at + self.reset_timeout_s
                          - self.clock.monotonic())
        self._fire_transitions()
        return out

    def allow(self) -> bool:
        """True if a call may proceed now (counts a rejection otherwise).
        In HALF_OPEN exactly ONE caller gets True (the probe); the rest
        are refused until its outcome is recorded — a recovering
        dependency meets one request, not a thundering herd."""
        with self._lock:
            self._maybe_half_open()
            if self._state == OPEN or (self._state == HALF_OPEN
                                       and self._probe_inflight):
                self.rejected += 1
                out = False
            else:
                if self._state == HALF_OPEN:
                    self._probe_inflight = True
                out = True
        self._fire_transitions()
        return out

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                logger.info("circuit %s closed after successful probe",
                            self.name)
            self._set_state(CLOSED)
        self._fire_transitions()

    def record_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            self._probe_inflight = False
            self._consecutive_failures += 1
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._set_state(OPEN)
                self._opened_at = self.clock.monotonic()
                self.trips += 1
                logger.warning(
                    "circuit %s OPEN after %d consecutive failures "
                    "(cool-down %.1fs)", self.name,
                    self._consecutive_failures, self.reset_timeout_s)
        self._fire_transitions()

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under the breaker: refused with
        :class:`CircuitOpenError` while OPEN, outcome recorded otherwise."""
        if not self.allow():
            raise CircuitOpenError(
                f"circuit {self.name} is open",
                retry_after=self.retry_after())
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out


# numeric encoding for breaker-state gauges (Prometheus has no enums)
STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


def metrics_transition_hook(registry=None) -> Callable[[str, str, str], None]:
    """An ``on_transition`` hook recording every breaker state change as
    ``breaker_transitions_total{breaker,from_state,to_state}``."""
    from . import metrics as _metrics
    reg = registry if registry is not None else _metrics.REGISTRY
    transitions = reg.counter(
        "breaker_transitions_total", "Circuit breaker state transitions",
        ("breaker", "from_state", "to_state"))

    def hook(name: str, old: str, new: str) -> None:
        transitions.inc(breaker=name, from_state=old, to_state=new)

    return hook


class NonFiniteGuard:
    """Budget for skipped non-finite training steps.

    The trainers detect non-finite gradients ON DEVICE and select the old
    params/opt-state (the update is a no-op); this host-side guard counts
    those skips, logs each one, fires ``on_step_skipped`` on the net's
    listeners, and raises once more than ``budget`` steps were skipped —
    a diverging run fails loudly instead of free-running on stale params.

    When the caller hands the failing batch to :meth:`step`, the guard
    additionally runs NaN layer-of-origin attribution
    (``util.health.attribute_nonfinite``): a diagnostic replay names the
    first offending layer/param, which is stamped into the skip reason,
    the ``on_step_skipped`` info dict, and the ``step_skipped`` flight
    event — so a skipped step explains WHERE the run diverged, not just
    that it did. ``attribute=False`` disables the replay (it costs one
    un-jitted forward+backward per skip).
    """

    def __init__(self, budget: int, net=None,
                 model_name: Optional[str] = None, attribute: bool = True):
        self.budget = int(budget)
        self.net = net
        self.model_name = model_name or (
            type(net).__name__ if net is not None else "net")
        self.attribute = attribute
        self.skipped = 0
        self.last_attribution = None

    def step(self, ok, detail: str = "", batch=None, params=None) -> None:
        """Record one step's device-computed finiteness flag. ``detail``
        qualifies partial skips (e.g. local-SGD, where only some replicas
        suppressed their update). ``batch`` is the (x, y, mask) the step
        consumed — when given, a skip triggers layer-of-origin
        attribution; ``params`` overrides the param tree the replay reads
        (callers whose step donated ``net.params`` pass the returned,
        still-valid tree)."""
        if bool(ok):
            return
        self.skipped += 1
        net = self.net
        iteration = getattr(net, "iteration_count", self.skipped)
        report = None
        if self.attribute and batch is not None and net is not None:
            try:
                from . import health as _health
                x, y, mask = (tuple(batch) + (None, None))[:3]
                report = _health.attribute_nonfinite(
                    net, x, y, mask, params=params,
                    model=self.model_name, iteration=iteration)
                self.last_attribution = report
            except Exception:
                logger.exception("NaN layer-of-origin attribution failed")
        reason = ("non-finite gradients" + (f" ({detail})" if detail else ""))
        if report is not None:
            reason += f" — {report.summary()}"
        info = {"model": self.model_name, "iteration": int(iteration),
                "layer": report.layer if report is not None else None,
                "quantity": report.quantity if report is not None else None,
                "param": report.param if report is not None else None}
        logger.warning(
            "%s at iteration %s — update suppressed (%d/%d budget)",
            reason, iteration, self.skipped, self.budget)
        from . import flightrecorder as _flight
        _flight.record("step_skipped", reason=reason, skipped=self.skipped,
                       budget=self.budget, **info)
        from ..optimize.listeners import fire_step_skipped
        for l in getattr(net, "listeners", []) or []:
            fire_step_skipped(l, net, iteration, reason, info)
        if self.skipped > self.budget:
            raise ResilienceError(
                f"{self.skipped} training steps skipped for non-finite "
                f"gradients (budget {self.budget}) — the run is "
                f"diverging{'; ' + report.summary() if report else ''}")
