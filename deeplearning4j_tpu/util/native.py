"""Native-kernel build/load: the framework's C++ runtime pattern.

The reference reaches native code through JavaCPP bindings to prebuilt
libraries (libnd4j, cuDNN — SURVEY §2.3); here host-side hot loops ship as
C++ sources compiled on first use with the system toolchain and bound via
ctypes. One loader serves every kernel (`clustering/_sptree.cpp`,
`datavec/_fastcsv.cpp`, ...): hash-keyed shared cache, atomic rename so
concurrent first users never dlopen a half-written .so, graceful None when
no compiler is available (callers keep a pure-Python fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import warnings
from pathlib import Path
from typing import Optional, Sequence

_cache: dict = {}


def compile_and_load(src: Path, *, flags: Sequence[str] = ()
                     ) -> Optional[ctypes.CDLL]:
    """Compile ``src`` (cached by content hash) and dlopen it; None on any
    failure (missing source, no g++, compile error) with a warning."""
    src = Path(src)
    key = (str(src), tuple(flags))
    if key in _cache:
        return _cache[key]
    lib = _build(src, tuple(flags))
    _cache[key] = lib
    return lib


def _build(src: Path, flags) -> Optional[ctypes.CDLL]:
    if not src.exists():
        return None
    digest = hashlib.sha256(src.read_bytes()
                            + " ".join(flags).encode()).hexdigest()[:16]
    out_dir = Path(tempfile.gettempdir()) / "dl4j_tpu_native"
    out_dir.mkdir(parents=True, exist_ok=True)
    so = out_dir / f"{src.stem}_{digest}.so"
    if not so.exists():
        # compile to a process-private name, then atomically rename: a
        # second process must never dlopen a half-written .so
        tmp = out_dir / f"{src.stem}_{digest}.{os.getpid()}.tmp.so"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++14",
               *flags, "-o", str(tmp), str(src)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        except Exception as e:
            warnings.warn(f"native build of {src.name} failed ({e}); "
                          "using the pure-Python fallback")
            return None
    try:
        return ctypes.CDLL(str(so))
    except OSError as e:
        warnings.warn(f"loading {so.name} failed ({e}); "
                      "using the pure-Python fallback")
        return None
