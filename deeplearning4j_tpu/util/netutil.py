"""Small shared predicates over network runtimes."""

from __future__ import annotations


def is_graph(net) -> bool:
    """True for ComputationGraph-shaped runtimes (DAG with a topo order),
    False for MultiLayerNetwork-shaped ones. Structural, so subclasses and
    wrappers classify correctly."""
    return hasattr(net, "topo_order")
