"""Small shared predicates over network runtimes."""

from __future__ import annotations


def is_graph(net) -> bool:
    """True for ComputationGraph-shaped runtimes (DAG with a topo order),
    False for MultiLayerNetwork-shaped ones. Structural, so subclasses and
    wrappers classify correctly."""
    return hasattr(net, "topo_order")


def streaming_cache_limit(net):
    """Smallest ``max_cache_t`` among the net's streaming-cached layers
    (attention K/V caches), or None when nothing carries a bounded cache.
    Feeding more total steps than this through ``rnn_time_step`` overflows
    the cache (the tail overwrites) — the runtimes count fed steps against
    it and warn instead of silently degrading."""
    if is_graph(net):
        layers = (getattr(v, "layer", None)
                  for v in net.conf.vertices.values())
    else:
        layers = net.layers
    limits = [l.max_cache_t for l in layers
              if l is not None and getattr(l, "max_cache_t", None) is not None]
    return min(limits) if limits else None


_UNSET = object()


def note_streamed_steps(net, t_new: int) -> None:
    """Host-side streaming overflow counter: add ``t_new`` fed steps to the
    net's tally and warn ONCE when the total first exceeds the smallest
    streaming cache (``max_cache_t``) — past that point the cache tail is
    overwritten and decoded positions silently stop matching the true
    global positions. Reset by ``rnn_clear_previous_state()``. The limit
    is memoized on the net: this runs once per token in decode loops, and
    cache sizes are fixed at layer-config time."""
    limit = getattr(net, "_stream_cache_limit_memo", _UNSET)
    if limit is _UNSET:
        limit = streaming_cache_limit(net)
        net._stream_cache_limit_memo = limit
    if limit is None:
        return
    prev = net._rnn_steps_fed
    net._rnn_steps_fed = prev + int(t_new)
    if net._rnn_steps_fed > limit >= prev:
        import warnings
        warnings.warn(
            f"rnn_time_step has been fed {net._rnn_steps_fed} total steps "
            f"but the smallest streaming K/V cache holds max_cache_t="
            f"{limit}; the cache tail is now overwritten and outputs no "
            "longer reflect true global positions — call "
            "rnn_clear_previous_state() between sequences or raise "
            "max_cache_t", RuntimeWarning, stacklevel=3)
