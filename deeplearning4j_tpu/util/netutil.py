"""Small shared predicates over network runtimes."""

from __future__ import annotations


class StreamingCacheOverflow(RuntimeError):
    """A strict-mode streaming K/V cache (``cache_overflow="strict"``)
    would overflow: the chunk about to be fed exceeds the remaining
    window. Raised host-side BEFORE the dispatch, so the cache is left
    untouched."""


def is_graph(net) -> bool:
    """True for ComputationGraph-shaped runtimes (DAG with a topo order),
    False for MultiLayerNetwork-shaped ones. Structural, so subclasses and
    wrappers classify correctly."""
    return hasattr(net, "topo_order")


def _streaming_layers(net):
    if is_graph(net):
        layers = (getattr(v, "layer", None)
                  for v in net.conf.vertices.values())
    else:
        layers = net.layers
    return [l for l in layers
            if l is not None and getattr(l, "max_cache_t", None) is not None]


def streaming_cache_limit(net):
    """Smallest ``max_cache_t`` among the net's streaming-cached layers
    (attention K/V caches), or None when nothing carries a bounded cache.
    Feeding more total steps than this through ``rnn_time_step`` slides
    the window (the oldest positions are evicted) — the runtimes count fed
    steps against it and warn instead of degrading silently."""
    limits = [l.max_cache_t for l in _streaming_layers(net)]
    return min(limits) if limits else None


def strict_cache_limit(net):
    """Smallest ``max_cache_t`` among streaming layers configured with
    ``cache_overflow="strict"``, or None when no layer is strict."""
    limits = [l.max_cache_t for l in _streaming_layers(net)
              if getattr(l, "cache_overflow", "evict") == "strict"]
    return min(limits) if limits else None


_UNSET = object()


def precheck_streamed_steps(net, t_new: int) -> None:
    """Strict-mode gate, called by ``rnn_time_step`` BEFORE the dispatch:
    when any streaming layer declares ``cache_overflow="strict"`` and the
    chunk about to be fed would push the total past its window, raise
    :class:`StreamingCacheOverflow` (leaving the cache untouched) instead
    of evicting. Memoized like the warn-path limit — this runs once per
    token in decode loops."""
    limit = getattr(net, "_stream_strict_limit_memo", _UNSET)
    if limit is _UNSET:
        limit = strict_cache_limit(net)
        net._stream_strict_limit_memo = limit
    if limit is None:
        return
    total = net._rnn_steps_fed + int(t_new)
    if total > limit:
        raise StreamingCacheOverflow(
            f"rnn_time_step would reach {total} total streamed steps but a "
            f"strict streaming K/V cache holds max_cache_t={limit}; call "
            "rnn_clear_previous_state() between sequences, raise "
            "max_cache_t, or set cache_overflow='evict' for "
            "sliding-window attention")


def note_streamed_steps(net, t_new: int) -> None:
    """Host-side streaming overflow counter: add ``t_new`` fed steps to the
    net's tally and warn ONCE when the total first exceeds the smallest
    streaming cache (``max_cache_t``) — past that point the oldest cached
    positions are EVICTED (sliding-window attention): outputs stay
    position-correct but attend only the most recent ``max_cache_t``
    steps. Reset by ``rnn_clear_previous_state()``. The limit is memoized
    on the net: this runs once per token in decode loops, and cache sizes
    are fixed at layer-config time."""
    limit = getattr(net, "_stream_cache_limit_memo", _UNSET)
    if limit is _UNSET:
        limit = streaming_cache_limit(net)
        net._stream_cache_limit_memo = limit
    if limit is None:
        return
    prev = net._rnn_steps_fed
    net._rnn_steps_fed = prev + int(t_new)
    if net._rnn_steps_fed > limit >= prev:
        import warnings
        warnings.warn(
            f"rnn_time_step has been fed {net._rnn_steps_fed} total steps "
            f"but the smallest streaming K/V cache holds max_cache_t="
            f"{limit}; the window now SLIDES — the oldest positions are "
            "evicted and outputs attend only the most recent "
            f"{limit} steps. Call rnn_clear_previous_state() between "
            "sequences, raise max_cache_t, or set "
            "cache_overflow='strict' to fail instead",
            RuntimeWarning, stacklevel=3)
