"""Tracing substrate: spans with parent links, JSONL export, seam context.

The companion of :mod:`deeplearning4j_tpu.util.metrics`: metrics say *how
often* and *how long* in aggregate; a trace says what ONE request did —
queue wait → batch assembly → model call as parented spans with wall +
monotonic timestamps.

Spans cross threads (an HTTP handler enqueues, the batcher answers), so
parenting is explicit: ``tracer.start(name, parent=...)`` / ``span.end()``
for cross-thread spans, and the ``tracer.span(...)`` context manager for
same-thread nesting (the active span is tracked per-thread and becomes
the default parent).

Chaos-test integration: entering ``span()`` stamps the active span into
the :mod:`deeplearning4j_tpu.util.faults` seam context, so a scripted
fault records WHICH span it landed in (``FaultPlan.trigger_context``) —
"the injected infer failure hit the model-call span of trace X" becomes
an assertable fact instead of a guess.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
import weakref
from typing import Any, Dict, List, Optional

from . import faults as _faults


class Span:
    """One timed operation. ``start_unix`` is wall time (for humans and
    cross-process alignment); durations come from the monotonic clock."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attributes",
                 "start_unix", "_start_mono", "duration_ms", "status",
                 "_tracer")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str],
                 attributes: Optional[Dict[str, Any]] = None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.start_unix = time.time()
        self._start_mono = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.status = "ok"

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def end(self, status: Optional[str] = None) -> None:
        """Close the span (idempotent) and hand it to the tracer."""
        if self.duration_ms is not None:
            return
        self.duration_ms = (time.perf_counter() - self._start_mono) * 1000.0
        if status is not None:
            self.status = status
        self._tracer._finish(self)

    def context(self) -> Dict[str, str]:
        """The identifying triple stamped into fault-seam payloads."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "name": self.name}

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start_unix": self.start_unix,
                "duration_ms": self.duration_ms, "status": self.status,
                "attributes": self.attributes}


class _ActiveStack(threading.local):
    def __init__(self):
        self.stack: List[Span] = []


class Tracer:
    """Creates spans and collects the finished ones for export.

    ``max_spans`` bounds memory: a long-lived server keeps the newest N
    finished spans (the export is a flight recorder, not an archive).
    """

    def __init__(self, max_spans: int = 10000):
        self.max_spans = int(max_spans)
        self._lock = threading.Lock()
        self._finished: List[Span] = []
        self._active = _ActiveStack()
        with _tracers_lock:
            _live_tracers.add(self)

    # -- creation ------------------------------------------------------

    def start(self, name: str, parent: Optional[Span] = None,
              attributes: Optional[Dict[str, Any]] = None) -> Span:
        """Explicit-lifetime span (cross-thread safe): caller must call
        ``span.end()``. Defaults the parent to this thread's active span."""
        if parent is None:
            parent = self.current()
        trace_id = parent.trace_id if parent else uuid.uuid4().hex
        return Span(self, name, trace_id,
                    parent.span_id if parent else None, attributes)

    def span(self, name: str, parent: Optional[Span] = None,
             attributes: Optional[Dict[str, Any]] = None):
        """Context manager: starts a span, makes it this thread's active
        span (and the fault-seam context), ends it on exit — status
        "error" if the block raised."""
        tracer = self
        s = self.start(name, parent, attributes)

        class _Ctx:
            def __enter__(self):
                tracer._active.stack.append(s)
                return s

            def __exit__(self, exc_type, exc, tb):
                stack = tracer._active.stack
                if stack and stack[-1] is s:
                    stack.pop()
                s.end("error" if exc_type is not None else None)
                return False

        return _Ctx()

    def current(self) -> Optional[Span]:
        """This thread's innermost open ``span()`` block."""
        stack = self._active.stack
        return stack[-1] if stack else None

    # -- collection / export -------------------------------------------

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._finished.append(span)
            if len(self._finished) > self.max_spans:
                del self._finished[:len(self._finished) - self.max_spans]

    @property
    def finished(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def find(self, name: str) -> List[Span]:
        return [s for s in self.finished if s.name == name]

    def to_jsonl(self) -> str:
        return "".join(json.dumps(s.to_dict()) + "\n"
                       for s in self.finished)

    def export_jsonl(self, path: str) -> int:
        """Write one JSON object per finished span; returns the count."""
        spans = self.finished
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(spans)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


# ---------------------------------------------------------------------------
# fault-seam context: faults.check() payloads carry the active span
# ---------------------------------------------------------------------------

_tracers_lock = threading.Lock()
_live_tracers: "weakref.WeakSet[Tracer]" = weakref.WeakSet()


def _seam_context() -> Dict[str, Any]:
    """Called by faults.check(): the active span of ANY live tracer on
    this thread (at most one — span() stacks are per-thread)."""
    with _tracers_lock:
        tracers = list(_live_tracers)
    for t in tracers:
        s = t.current()
        if s is not None:
            return {"span": s.context()}
    return {}


_faults.add_context_provider(_seam_context)
