"""Tracing substrate: spans with parent links, JSONL export, seam context,
and serializable cross-scope propagation.

The companion of :mod:`deeplearning4j_tpu.util.metrics`: metrics say *how
often* and *how long* in aggregate; a trace says what ONE request did —
queue wait → batch assembly → model call as parented spans with wall +
monotonic timestamps.

Spans cross threads (an HTTP handler enqueues, the batcher answers), so
parenting is explicit: ``tracer.start(name, parent=...)`` / ``span.end()``
for cross-thread spans, and the ``tracer.span(...)`` context manager for
same-thread nesting (the active span is tracked per-thread and becomes
the default parent).

Spans also cross PROCESSES and HTTP hops (Dapper-style context
propagation, Sigelman et al. 2010): every span carries ``host`` and
``pid`` next to its ids, and :func:`inject` / :func:`extract` serialize
the identifying pair as a W3C-traceparent-style string
(``00-<trace_id>-<span_id>-01``) that rides an environment variable into
a forked fleet child or a ``traceparent`` HTTP header into a server. The
extracted :class:`SpanContext` is a valid ``parent=`` for
``tracer.start`` — the remote child's spans join the caller's trace, and
:mod:`deeplearning4j_tpu.util.timeline` merges the per-process exports
into one fleet/request timeline.

Chaos-test integration: entering ``span()`` stamps the active span into
the :mod:`deeplearning4j_tpu.util.faults` seam context, so a scripted
fault records WHICH span it landed in (``FaultPlan.trigger_context``) —
"the injected infer failure hit the model-call span of trace X" becomes
an assertable fact instead of a guess. The same provider feeds the
flight recorder: every flight event recorded while a span is active
carries the active ``trace_id``/``span_id``, so a watchdog or crash dump
cross-references the exact request or round it interrupted.

Memory: a tracer keeps the newest ``max_spans`` finished spans (default
10000, ``DL4JTPU_TRACE_MAX_SPANS``); overflow drops the OLDEST spans,
counted in ``tracer_spans_dropped_total`` with a one-time warning — the
export is a flight recorder, not an archive, but the drop must be
visible.
"""

from __future__ import annotations

import json
import logging
import os
import random
import re
import socket
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from . import faults as _faults
from . import flightrecorder as _flight
from . import metrics as _metrics

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_MAX_SPANS = 10000

_HOSTNAME = socket.gethostname()

# W3C traceparent: version "00", 32-hex trace id, 16-hex span id, flags.
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

# The one env var a parent process sets to adopt its children's traces
# (fleet harness, two-process workers): extract() of its value is the
# root parent for the child's top-level span.
TRACEPARENT_ENV = "DL4JTPU_TRACEPARENT"


# Span ids are hot-path allocations (one per decode block per lane):
# a process-seeded PRNG at ~0.1µs/id replaces uuid4's ~3µs urandom
# syscall. Spawned processes reseed at import; os.fork()-style children
# (multiprocessing's default on Linux) inherit the parent's PRNG state,
# so reseed after fork — identical id streams would collide in merged
# timelines (the collector dedupes by span_id).
_id_rng = random.Random(int.from_bytes(os.urandom(16), "big"))
_id_lock = threading.Lock()


def _reseed_ids() -> None:
    with _id_lock:
        _id_rng.seed(int.from_bytes(os.urandom(16), "big"))


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed_ids)


def _new_trace_id() -> str:
    with _id_lock:
        return f"{_id_rng.getrandbits(128):032x}"


def _new_span_id() -> str:
    with _id_lock:
        return f"{_id_rng.getrandbits(64):016x}"


def _max_spans_default() -> int:
    n = int(os.environ.get("DL4JTPU_TRACE_MAX_SPANS",
                           str(DEFAULT_MAX_SPANS)))
    if n < 1:
        raise ValueError(f"DL4JTPU_TRACE_MAX_SPANS must be >= 1, got {n}")
    return n


def dropped_spans_counter(registry=None) -> "_metrics.Counter":
    return (registry if registry is not None
            else _metrics.REGISTRY).counter(
        "tracer_spans_dropped_total",
        "Finished spans evicted from a tracer's bounded ring (oldest "
        "first; raise DL4JTPU_TRACE_MAX_SPANS if the drop loses data "
        "an export needed)")


class SpanContext:
    """The serializable identifying pair of a span — what crosses a
    process or HTTP boundary. Valid as ``parent=`` for
    :meth:`Tracer.start` (parenting only needs ``trace_id``/``span_id``)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id!r}, {self.span_id!r})"

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)


def inject(span) -> str:
    """Serialize a span's (or SpanContext's) identity as a W3C-
    traceparent-style string: ``00-<trace_id>-<span_id>-01``."""
    return f"00-{span.trace_id}-{span.span_id}-01"


def extract(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent string back into a :class:`SpanContext`;
    None for a missing or malformed value (propagation is best-effort —
    a bad header starts a fresh trace, it never breaks the request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    return SpanContext(m.group(1), m.group(2))


def env_context() -> Optional[SpanContext]:
    """The trace context a parent process handed this one via
    ``DL4JTPU_TRACEPARENT`` (fleet children, spawned workers)."""
    return extract(os.environ.get(TRACEPARENT_ENV))


class Span:
    """One timed operation. ``start_unix`` is wall time (for humans and
    cross-process alignment); durations come from the monotonic clock.
    ``host``/``pid`` name the process that produced the span, so merged
    multi-process timelines keep their provenance."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attributes",
                 "start_unix", "_start_mono", "duration_ms", "status",
                 "host", "pid", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str],
                 attributes: Optional[Dict[str, Any]] = None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.host = tracer.host
        self.pid = os.getpid()
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.start_unix = time.time()
        self._start_mono = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.status = "ok"

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def end(self, status: Optional[str] = None) -> None:
        """Close the span (idempotent) and hand it to the tracer."""
        if self.duration_ms is not None:
            return
        self.duration_ms = (time.perf_counter() - self._start_mono) * 1000.0
        if status is not None:
            self.status = status
        self._tracer._finish(self)

    def context(self) -> Dict[str, str]:
        """The identifying triple stamped into fault-seam payloads."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "name": self.name}

    def traceparent(self) -> str:
        return inject(self)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "host": self.host, "pid": self.pid,
                "start_unix": self.start_unix,
                "duration_ms": self.duration_ms, "status": self.status,
                "attributes": self.attributes}


class _ActiveStack(threading.local):
    def __init__(self):
        self.stack: List[Span] = []


class Tracer:
    """Creates spans and collects the finished ones for export.

    ``max_spans`` bounds memory: a long-lived server keeps the newest N
    finished spans (default from ``DL4JTPU_TRACE_MAX_SPANS``); overflow
    increments ``tracer_spans_dropped_total`` and warns once. ``host``
    names this tracer's process in exported spans — a logical id (an
    elastic fleet host) when given, the machine hostname otherwise.
    """

    def __init__(self, max_spans: Optional[int] = None, *,
                 host: Optional[str] = None, registry=None):
        self.max_spans = (_max_spans_default() if max_spans is None
                          else max(1, int(max_spans)))
        self.host = host if host is not None else _HOSTNAME
        self._dropped_counter = dropped_spans_counter(registry)
        self._warned_drop = False
        self._lock = threading.Lock()
        self._finished: List[Span] = []
        self._active = _ActiveStack()
        with _tracers_lock:
            _live_tracers.add(self)

    # -- creation ------------------------------------------------------

    def start(self, name: str, parent: Optional[Any] = None,
              attributes: Optional[Dict[str, Any]] = None) -> Span:
        """Explicit-lifetime span (cross-thread safe): caller must call
        ``span.end()``. Defaults the parent to this thread's active span.
        ``parent`` may be a :class:`Span` or an extracted
        :class:`SpanContext` from another process."""
        if parent is None:
            parent = self.current()
        trace_id = parent.trace_id if parent else _new_trace_id()
        return Span(self, name, trace_id,
                    parent.span_id if parent else None, attributes)

    def span(self, name: str, parent: Optional[Any] = None,
             attributes: Optional[Dict[str, Any]] = None):
        """Context manager: starts a span, makes it this thread's active
        span (and the fault-seam context), ends it on exit — status
        "error" if the block raised."""
        tracer = self
        s = self.start(name, parent, attributes)

        class _Ctx:
            def __enter__(self):
                tracer._active.stack.append(s)
                return s

            def __exit__(self, exc_type, exc, tb):
                stack = tracer._active.stack
                if stack and stack[-1] is s:
                    stack.pop()
                s.end("error" if exc_type is not None else None)
                return False

        return _Ctx()

    def record(self, name: str, seconds: float,
               parent: Optional[Any] = None,
               attributes: Optional[Dict[str, Any]] = None) -> Span:
        """An already-finished span of explicit duration ending NOW —
        for phases whose boundaries were measured inline (a poll loop's
        successful tail) rather than wrapped in a context manager."""
        s = self.start(name, parent, attributes)
        seconds = max(0.0, float(seconds))
        s.start_unix -= seconds
        s.duration_ms = seconds * 1000.0
        self._finish(s)
        return s

    def current(self) -> Optional[Span]:
        """This thread's innermost open ``span()`` block."""
        stack = self._active.stack
        return stack[-1] if stack else None

    # -- collection / export -------------------------------------------

    def _finish(self, span: Span) -> None:
        dropped = 0
        with self._lock:
            self._finished.append(span)
            if len(self._finished) > self.max_spans:
                dropped = len(self._finished) - self.max_spans
                del self._finished[:dropped]
        if dropped:
            self._dropped_counter.inc(dropped)
            if not self._warned_drop:
                self._warned_drop = True
                logger.warning(
                    "tracer span ring full (max_spans=%d): dropping "
                    "oldest finished spans — raise DL4JTPU_TRACE_MAX_SPANS "
                    "to keep more (counted in tracer_spans_dropped_total)",
                    self.max_spans)

    @property
    def finished(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def find(self, name: str) -> List[Span]:
        return [s for s in self.finished if s.name == name]

    def to_jsonl(self) -> str:
        return "".join(json.dumps(s.to_dict()) + "\n"
                       for s in self.finished)

    def export_jsonl(self, path: str) -> int:
        """Write one JSON object per finished span; returns the count."""
        spans = self.finished
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(spans)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


# ---------------------------------------------------------------------------
# process-default tracer + active-span context for the other sinks
# ---------------------------------------------------------------------------

# RLock, not Lock: flightrecorder.record() runs from SIGNAL HANDLERS
# (PreemptionHandler) and now consults active_span() via the context
# provider — if the signal lands while the main thread is inside
# Tracer.__init__ or active_span() holding this lock, a plain lock
# would self-deadlock the drain path
_tracers_lock = threading.RLock()
_live_tracers: "weakref.WeakSet[Tracer]" = weakref.WeakSet()


def active_span() -> Optional[Span]:
    """The active span of ANY live tracer on this thread (at most one —
    ``span()`` stacks are per-thread)."""
    with _tracers_lock:
        tracers = list(_live_tracers)
    for t in tracers:
        s = t.current()
        if s is not None:
            return s
    return None


# The process-default tracer: components take ``tracer=None`` and fall
# back to it, so one export shows the whole process.
TRACER = Tracer()


def default_tracer() -> Tracer:
    return TRACER


def _seam_context() -> Dict[str, Any]:
    """Called by faults.check(): fault-seam triggers carry the active
    span (and through it the trace id the fault interrupted)."""
    s = active_span()
    return {"span": s.context()} if s is not None else {}


_faults.add_context_provider(_seam_context)


def _flight_context() -> Dict[str, Any]:
    """Called by flightrecorder.record(): every event recorded under an
    active span carries the trace it belongs to, so a crash/watchdog
    dump names the exact request or round it interrupted."""
    s = active_span()
    if s is None:
        return {}
    return {"trace_id": s.trace_id, "span_id": s.span_id}


_flight.add_context_provider(_flight_context)
