"""Flight recorder: a bounded in-memory ring of structured events that is
dumped as JSONL when a run crashes, stalls, or is preempted.

The reference's answer to "what was the fleet doing when it died" was
Spark's event log; the metrics/tracing substrate (PR 2) answers *how
often* and *how long* but not *what happened just before the crash* — a
Prometheus scrape cannot be taken from a wedged process. This module is
the black box: every layer records its significant events (step
dispatches, retraces, breaker transitions, fault-seam triggers,
checkpoint commits, decode shed/retire summaries) into one process-wide
bounded ring, near-free in steady state, and the failure paths —
:class:`~deeplearning4j_tpu.util.durable.StepWatchdog` expiry,
:class:`~deeplearning4j_tpu.util.durable.PreemptionHandler` SIGTERM, and
an optional unhandled-exception hook — dump the ring to a JSONL file a
human (or the chaos harness) reads after the process is gone.

Event schema: one JSON object per line, always carrying
``{"seq": N, "t": unix_seconds, "kind": str}`` plus kind-specific fields
(see ARCHITECTURE.md "Performance attribution & flight recorder" for the
kinds recorded in-tree). Fields that fail JSON serialization are
stringified rather than dropped — a dump must never raise.

Knobs: ``DL4JTPU_FLIGHT_EVENTS`` (ring capacity, default 512),
``DL4JTPU_FLIGHT_DIR`` (dump directory, default the system temp dir).
Live inspection: ``GET /debug/flightrecorder`` on the serving and UI
servers returns the current ring as JSON.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_CAPACITY = 512

# Context providers: called on every record() to stamp ambient context
# (e.g. the active trace id, registered by util.tracing) into the event.
# Provider fields never override the caller's explicit fields, and a
# failing provider is ignored — recording must never raise.
_context_providers: List = []


def add_context_provider(fn) -> None:
    """Register a zero-arg callable returning a dict of extra fields for
    every recorded event (same shape as faults.add_context_provider)."""
    _context_providers.append(fn)


def _ambient_context() -> Dict:
    out: Dict = {}
    for fn in _context_providers:
        try:
            out.update(fn() or {})
        except Exception:
            pass
    return out


def _capacity_default() -> int:
    n = int(os.environ.get("DL4JTPU_FLIGHT_EVENTS", str(DEFAULT_CAPACITY)))
    if n < 1:
        raise ValueError(f"DL4JTPU_FLIGHT_EVENTS must be >= 1, got {n}")
    return n


def dump_dir() -> str:
    """Where dumps land: ``DL4JTPU_FLIGHT_DIR`` or the system temp dir."""
    return os.environ.get("DL4JTPU_FLIGHT_DIR") or tempfile.gettempdir()


class FlightRecorder:
    """Thread-safe bounded ring of structured events.

    ``record()`` is the steady-state hot path: one lock, one deque
    append, no I/O. ``dump()`` is the failure path: serialize the ring
    to JSONL, best-effort (logs instead of raising — the recorder must
    never turn a crash into a different crash).
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = (_capacity_default() if capacity is None
                         else max(1, int(capacity)))
        # RLock, not Lock: PreemptionHandler records/dumps from a SIGNAL
        # HANDLER, which Python runs on the main thread — if the signal
        # lands while that same thread is inside record() (the fit loop
        # records every step), a plain lock would self-deadlock the
        # graceful-drain path
        self._lock = threading.RLock()
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self.last_dump_path: Optional[str] = None

    # -- recording -----------------------------------------------------

    def record(self, kind: str, /, **fields) -> dict:
        event = {"seq": 0, "t": time.time(), "kind": str(kind),
                 **_ambient_context(), **fields}
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
        return event

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- dumping -------------------------------------------------------

    def to_jsonl(self) -> str:
        return "".join(json.dumps(e, default=repr) + "\n"
                       for e in self.events())

    def default_dump_path(self) -> str:
        return os.path.join(dump_dir(), f"flightrecorder_{os.getpid()}.jsonl")

    def dump(self, path: Optional[str] = None,
             reason: Optional[str] = None) -> Optional[str]:
        """Write the ring as JSONL (appending a final ``dump`` event naming
        the reason). Returns the written path, or None on failure — a
        failing dump is logged, never raised, so the crash/stall that
        triggered it still surfaces as itself."""
        if reason is not None:
            self.record("dump", reason=reason)
        path = path or self.default_dump_path()
        try:
            body = self.to_jsonl()
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                f.write(body)
            self.last_dump_path = path
            logger.warning("flight recorder dumped %d events to %s",
                           len(self), path)
            return path
        except Exception:
            logger.exception("flight recorder dump to %s failed", path)
            return None


# The process-default recorder: the black box every in-tree feed records
# into, so one dump explains the whole process.
RECORDER = FlightRecorder()


def record(kind: str, /, **fields) -> dict:
    return RECORDER.record(kind, **fields)


def events(kind: Optional[str] = None) -> List[dict]:
    return RECORDER.events(kind)


def jsonable_events(kind: Optional[str] = None) -> List[dict]:
    """Events with every field JSON-safe (repr-stringified when needed) —
    what the HTTP debug endpoints return, so one odd field value cannot
    500 the black-box inspection exactly when someone needs it."""
    return [json.loads(json.dumps(e, default=repr))
            for e in RECORDER.events(kind)]


def dump(reason: Optional[str] = None,
         path: Optional[str] = None) -> Optional[str]:
    return RECORDER.dump(path=path, reason=reason)


def read_jsonl(path: str) -> List[dict]:
    """Parse a dump back into events (the chaos harness's read side)."""
    out = []
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# ----------------------------------------------------------------------
# unhandled-exception hook
# ----------------------------------------------------------------------

_hook_lock = threading.Lock()
_hook_installed = False


def install_excepthook() -> None:
    """Chain ``sys.excepthook`` so an unhandled exception dumps the ring
    before the interpreter's (or anyone else's) handler runs. Idempotent."""
    global _hook_installed
    with _hook_lock:
        if _hook_installed:
            return
        previous = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                RECORDER.record("unhandled_exception",
                                error=f"{exc_type.__name__}: {exc}")
                RECORDER.dump(reason="unhandled_exception")
            except Exception:
                pass
            previous(exc_type, exc, tb)

        sys.excepthook = hook
        _hook_installed = True
