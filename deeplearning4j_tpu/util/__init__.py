"""Utilities: model serialization (checkpoint/resume), pytree helpers.

Parity: reference ``deeplearning4j-nn/.../util/`` — chiefly
``ModelSerializer.java:47-120`` (write) / ``:158-280`` (restore).
"""

from .serialization import ModelSerializer, load_model, save_model
from .recovery import CheckpointRecovery, RecoverableTrainer
from . import profiling
from . import metrics
from . import tracing
from . import flightrecorder
from .metrics import REGISTRY, MetricsRegistry
from .tracing import Tracer
from .durable import (AsyncCheckpointWriter, CheckpointStore,
                      DurableSession, DurableTrainer, PreemptionHandler,
                      StepWatchdog, TrainingState, WatchdogTimeout,
                      is_seekable)

__all__ = ["ModelSerializer", "save_model", "load_model",
           "CheckpointRecovery", "RecoverableTrainer", "profiling",
           "metrics", "tracing", "flightrecorder", "REGISTRY",
           "MetricsRegistry", "Tracer",
           "AsyncCheckpointWriter", "CheckpointStore", "DurableSession",
           "DurableTrainer", "PreemptionHandler", "StepWatchdog",
           "TrainingState", "WatchdogTimeout", "is_seekable"]
