"""Metrics substrate: labeled counters/gauges/histograms + Prometheus text.

Parity-plus: the reference's whole L7 (StatsListener → StatsStorage → UI)
exists to make *training* observable; nothing in it can answer "why did
this 503 happen" for the serving/resilience layers this reproduction
added. This module is the one process-wide metrics plane every layer
records into — serving request latencies, breaker transitions, retry
give-ups, training phase timings — exposed in Prometheus text format
(``registry.expose()``) so an off-the-shelf scraper explains every slow
step and every shed request.

Design:

- :class:`MetricsRegistry` — thread-safe, name-keyed. ``counter()`` /
  ``gauge()`` / ``histogram()`` are get-or-create (idempotent across call
  sites; re-declaring a name as a different type or label set raises).
- :class:`Counter` — monotonic; ``inc()``, per-labelset children via
  ``labels()``.
- :class:`Gauge` — ``set``/``inc``/``dec``, plus ``set_function`` for
  live values (queue depth, breaker state) sampled at exposition time.
- :class:`Histogram` — explicit buckets, cumulative ``_bucket`` series +
  ``_sum`` + ``_count`` (the Prometheus shape, so quantiles are the
  scraper's job, not the process's).
- ``REGISTRY`` — the process-default registry; components take an
  optional ``registry=`` and fall back to it.

Everything is pure stdlib and allocation-light: one dict lookup + one
lock per record on the hot path, nothing on import.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Prometheus default buckets suit RPC latencies in seconds.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0, +Inf for
    infinity, repr-precision otherwise."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_key(labelnames: Tuple[str, ...], labels: Dict[str, str]
                ) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


def _render_labels(labelnames: Sequence[str], values: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label_value(v)}"'
             for n, v in zip(labelnames, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label_value(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    """Base: a named family of per-labelset series."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for l in labelnames:
            if not _LABEL_RE.match(l):
                raise ValueError(f"invalid label name {l!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    # exposition -------------------------------------------------------

    def _samples(self) -> List[str]:
        raise NotImplementedError

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        lines.extend(self._samples())
        return "\n".join(lines)

    def snapshot(self) -> dict:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process)."""

    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum across every labelset (back-compat for bare-int counters)."""
        with self._lock:
            return sum(self._values.values())

    def _samples(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        return [f"{self.name}{_render_labels(self.labelnames, k)} {_fmt(v)}"
                for k, v in items]

    def snapshot(self) -> dict:
        with self._lock:
            series = [{"labels": dict(zip(self.labelnames, k)), "value": v}
                      for k, v in sorted(self._values.items())]
        return {"type": "counter", "help": self.help, "series": series}


class Gauge(_Metric):
    """A value that goes up and down; may be backed by a live callback
    (``set_function``) sampled at exposition time."""

    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fns: Dict[Tuple[str, ...], Callable[[], float]] = {}

    def set(self, value: float, **labels) -> None:
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float], **labels) -> None:
        """Sample ``fn`` at exposition time — the right shape for values
        that already live somewhere (queue depth, breaker state)."""
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            self._fns[key] = fn

    def value(self, **labels) -> float:
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                return self._values.get(key, 0.0)
        return float(fn())

    def _items(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            static = dict(self._values)
            fns = dict(self._fns)
        for key, fn in fns.items():
            try:
                static[key] = float(fn())
            except Exception:
                static.pop(key, None)   # a dead callback drops its series
        return sorted(static.items())

    def _samples(self) -> List[str]:
        return [f"{self.name}{_render_labels(self.labelnames, k)} {_fmt(v)}"
                for k, v in self._items()]

    def snapshot(self) -> dict:
        return {"type": "gauge", "help": self.help,
                "series": [{"labels": dict(zip(self.labelnames, k)),
                            "value": v} for k, v in self._items()]}


class Histogram(_Metric):
    """Explicit-bucket histogram: cumulative ``_bucket{le=...}`` counts
    plus ``_sum`` and ``_count`` per labelset."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        if bs != sorted(set(bs)):
            raise ValueError("duplicate bucket bounds")
        self.buckets = tuple(bs)        # +Inf is implicit
        # per-labelset: ([count per finite bucket], inf_count, sum)
        self._series: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, **labels) -> None:
        key = _labels_key(self.labelnames, labels)
        v = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * len(self.buckets), 0, 0.0]
            counts, _inf, _sum = s
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                s[1] += 1
            s[2] += v

    def count(self, **labels) -> int:
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            s = self._series.get(key)
            return 0 if s is None else sum(s[0]) + s[1]

    def sum(self, **labels) -> float:
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            s = self._series.get(key)
            return 0.0 if s is None else s[2]

    def total_sum(self) -> float:
        """Sum of observed values across EVERY labelset (e.g. compile
        wall across all jitted functions)."""
        with self._lock:
            return sum(s[2] for s in self._series.values())

    def _samples(self) -> List[str]:
        with self._lock:
            series = {k: [list(s[0]), s[1], s[2]]
                      for k, s in sorted(self._series.items())}
        out = []
        for key, (counts, inf_count, total) in series.items():
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lbl = _render_labels(self.labelnames, key, ("le", _fmt(b)))
                out.append(f"{self.name}_bucket{lbl} {cum}")
            cum += inf_count
            lbl = _render_labels(self.labelnames, key, ("le", "+Inf"))
            out.append(f"{self.name}_bucket{lbl} {cum}")
            plain = _render_labels(self.labelnames, key)
            out.append(f"{self.name}_sum{plain} {_fmt(total)}")
            out.append(f"{self.name}_count{plain} {cum}")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            series = [{"labels": dict(zip(self.labelnames, k)),
                       "buckets": dict(zip(map(_fmt, self.buckets), s[0])),
                       "inf": s[1], "sum": s[2],
                       "count": sum(s[0]) + s[1]}
                      for k, s in sorted(self._series.items())]
        return {"type": "histogram", "help": self.help,
                "bucket_bounds": list(self.buckets), "series": series}


class MetricsRegistry:
    """Thread-safe, name-keyed metric store with get-or-create accessors
    and Prometheus text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames, **kw)
                return m
        if not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}")
        if m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{m.labelnames}, not {tuple(labelnames)}")
        want_buckets = kw.get("buckets")
        if (want_buckets is not None
                and m.buckets != tuple(sorted(float(b)
                                              for b in want_buckets))):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{m.buckets}, not {tuple(want_buckets)}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def expose(self) -> str:
        """Prometheus text format (content type
        ``text/plain; version=0.0.4``), families sorted by name."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        return "\n".join(m.expose() for m in metrics) + ("\n" if metrics
                                                         else "")

    def snapshot(self) -> dict:
        """JSON-friendly dump (ridden by bench.py into BENCH_*.json)."""
        with self._lock:
            metrics = [(n, self._metrics[n]) for n in sorted(self._metrics)]
        return {n: m.snapshot() for n, m in metrics}


EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def write_exposition(handler, registry: "MetricsRegistry") -> None:
    """Write a registry's exposition as the HTTP response on a
    ``BaseHTTPRequestHandler`` — the one copy of the /metrics plumbing
    shared by the serving and UI servers."""
    body = registry.expose().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", EXPOSITION_CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)

# The process-default registry: components take ``registry=None`` and fall
# back to this, so one scrape shows the whole process.
REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY
