"""Timeline collector: merge per-process span exports into one ordered
fleet timeline (per elastic round) or per-request decode timeline.

The tracing substrate (:mod:`.tracing`) produces spans per PROCESS; this
module is the read side that crosses the process boundary, in the
Dapper/MegaScale shape: every participant exports its spans (per-host
JSONL from a tracer, ``trace_<host>.json`` records an elastic host
publishes next to its round's REDUCE record), and the collector merges
them by ``trace_id``/``round`` into one report that names, per round,
the CRITICAL-PATH host and the phase it spent its time in — the
full-attribution upgrade of the flight recorder's "stall names the
blocking host" event.

Inputs are deliberately forgiving: a host killed mid-run exported only
the rounds it finished (the store records survive the process), a
replayed round overwrote its record with the replay's timings, and a
round with no REDUCE record yet still renders from whatever spans exist.
Wall-clock (``start_unix``) orders events ACROSS hosts — adequate within
one machine or an NTP-disciplined fleet; skew shows up as impossible
orderings, not wrong durations (durations are monotonic-clock).

Three entry points:

- :func:`build_fleet_timeline` — store + JSONL exports → per-round
  attribution report (``python -m deeplearning4j_tpu.util.timeline``).
- :func:`request_timelines` — a tracer's decode spans → one nested
  timeline per served request (TTFT decomposition attached by the
  scheduler, see ``serving/decode.py``).
- :func:`trace_summaries` — everything a tracer holds, grouped by trace
  and nested by parentage (``GET /debug/timeline`` on both servers).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# phases whose spans gate a host's publish for round r: its own compute
# this round, plus the previous round's correction tail that delayed
# this round's start
_ROUND_PHASES = ("local_steps", "publish")
_PREV_TAIL_PHASES = ("wait", "reduce", "apply")


def _as_dict(span) -> dict:
    return span if isinstance(span, dict) else span.to_dict()


def _end_unix(s: dict) -> float:
    return float(s.get("start_unix") or 0.0) + \
        float(s.get("duration_ms") or 0.0) / 1000.0


def load_jsonl(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _expand_jsonl(patterns: Sequence[str]) -> List[str]:
    """Globs expanded forgivingly: an unmatched pattern contributes
    nothing (a fleet where every child died before exporting must still
    render from the store records), a literal existing path passes
    through."""
    import os as _os
    out = []
    for pattern in patterns:
        matched = sorted(_glob.glob(pattern))
        if matched:
            out.extend(matched)
        elif _os.path.exists(pattern):
            out.append(pattern)
    return out


def _dedupe(spans: Iterable[dict]) -> List[dict]:
    seen, out = set(), []
    for s in spans:
        sid = s.get("span_id")
        if sid in seen:
            continue
        seen.add(sid)
        out.append(s)
    return out


# ----------------------------------------------------------------------
# fleet timeline
# ----------------------------------------------------------------------

def _coerce_store(store):
    if store is None or not isinstance(store, str):
        return store
    from ..parallel.elastic import FileCoordinationStore
    return FileCoordinationStore(store)


def _store_rounds(store) -> List[Tuple[int, List[dict], Optional[dict]]]:
    """(round, trace_records, reduce_record) per dense round, from 0."""
    out = []
    r = 0
    while True:
        prefix = f"rounds/r{r:06d}"
        keys = store.list(prefix)
        if not keys:
            break
        traces, reduce_rec = [], None
        for key in keys:
            name = key.rsplit("/", 1)[-1]
            if name == "REDUCE.json":
                reduce_rec = store.get_json(key)
            elif name.startswith("trace_") and name.endswith(".json"):
                doc = store.get_json(key)
                if doc is not None:
                    traces.append(doc)
        out.append((r, traces, reduce_rec))
        r += 1
    return out


def _membership_log(store) -> List[dict]:
    recs = []
    for key in store.list("log"):
        doc = store.get_json(key)
        if doc is not None:
            recs.append(doc)
    recs.sort(key=lambda d: int(d.get("seq", 0)))
    return recs


def _round_of(span: dict) -> Optional[int]:
    r = (span.get("attributes") or {}).get("round")
    return None if r is None else int(r)


def build_fleet_timeline(store=None, jsonl_paths: Sequence[str] = (),
                         spans: Optional[Iterable] = None) -> dict:
    """Merge an elastic run's trace exports into one fleet timeline.

    ``store`` is the run's coordination store (object or directory
    path); ``jsonl_paths`` are per-host tracer exports (globs allowed);
    ``spans`` adds in-memory spans (Span objects or dicts). Any subset
    works — store records cover rounds the process died before
    exporting, JSONL covers spans the store never saw.

    Per round the report names the critical-path host and phase:

    - a host hard-evicted while the round was blocked on it
      (``blocked_round`` on the eviction record) → ``evicted``;
    - a member with no publish span in the merged set → ``missing``
      (it gated the reduce and left no trace);
    - otherwise the member whose ``publish`` ended last, attributed to
      its longest gating phase — this round's ``local_steps``/``publish``
      or the previous round's ``wait``/``reduce``/``apply`` tail that
      delayed this round's start. A wait-dominated critical host means
      the real bottleneck is upstream (it was itself blocked).
    """
    store = _coerce_store(store)
    all_spans: List[dict] = [_as_dict(s) for s in (spans or [])]
    for p in _expand_jsonl(jsonl_paths):
        all_spans.extend(load_jsonl(p))

    reduce_recs: Dict[int, dict] = {}
    # (round, host) -> spans, merged from store records + JSONL exports
    by_rh: Dict[Tuple[int, str], List[dict]] = {}
    incarnations: Dict[Tuple[int, str], int] = {}
    log: List[dict] = []
    if store is not None:
        for r, traces, reduce_rec in _store_rounds(store):
            if reduce_rec is not None:
                reduce_recs[r] = reduce_rec
            for rec in traces:
                h = rec.get("host")
                by_rh.setdefault((r, h), []).extend(rec.get("spans") or [])
                if rec.get("incarnation") is not None:
                    incarnations[(r, h)] = int(rec["incarnation"])
        log = _membership_log(store)
    # JSONL spans group by their CONTAINING round (parent link), same as
    # the store records — a wait span's ``round`` attribute names the
    # round it waited FOR (j = r - s), not the round it ran in
    round_of_span: Dict[str, Tuple[int, str]] = {}
    for s in all_spans:
        if s.get("name") == "elastic.round" and _round_of(s) is not None:
            round_of_span[s["span_id"]] = (_round_of(s), s.get("host"))
    for s in all_spans:
        name = s.get("name")
        if name == "elastic.round":
            key = round_of_span[s["span_id"]]
        elif name in _ROUND_PHASES + _PREV_TAIL_PHASES:
            key = round_of_span.get(s.get("parent_id"))
            if key is None and name in _ROUND_PHASES:
                # round span lost (truncated export): local_steps and
                # publish carry their containing round themselves
                r = _round_of(s)
                key = None if r is None else (r, s.get("host"))
            if key is None:
                continue        # tail-flush/catchup span outside a round
        else:
            continue
        by_rh.setdefault(key, []).append(s)
    for key, group in by_rh.items():
        by_rh[key] = sorted(_dedupe(group),
                            key=lambda s: s.get("start_unix") or 0.0)

    rounds = sorted({r for r, _h in by_rh} | set(reduce_recs))
    hosts = sorted({h for _r, h in by_rh if h})
    evicts = [rec for rec in log if rec.get("event") == "evict"]

    def _spans_of(r: int, h: str, names: Tuple[str, ...]) -> List[dict]:
        return [s for s in by_rh.get((r, h), ())
                if s.get("name") in names]

    out_rounds = []
    for r in rounds:
        reduce_rec = reduce_recs.get(r)
        members = (list(reduce_rec["members"]) if reduce_rec
                   else sorted({h for (rr, h) in by_rh if rr == r}))
        host_rows: Dict[str, dict] = {}
        for h in sorted({h for (rr, h) in by_rh if rr == r} |
                        set(members)):
            group = by_rh.get((r, h), [])
            round_spans = sorted(
                [s for s in group if s.get("name") == "elastic.round"],
                key=lambda s: s.get("start_unix") or 0.0)
            # an interrupted-then-resumed round leaves spans from BOTH
            # attempts in a same-process tracer export: the row reports
            # the LATEST attempt (phase spans selected by parentage),
            # not a sum over attempts
            if round_spans:
                rs = round_spans[-1]
                phase_spans = [s for s in group
                               if s.get("parent_id") == rs["span_id"]]
            else:
                rs = None
                phase_spans = [s for s in group
                               if s.get("name") != "elastic.round"]
            phases: Dict[str, float] = {}
            for s in phase_spans:
                phases[s["name"]] = (phases.get(s["name"], 0.0)
                                     + float(s.get("duration_ms") or 0.0))
            row = {"phases_ms": {k: round(v, 3)
                                 for k, v in phases.items()},
                   "member": h in members}
            if rs is not None:
                row.update(start_unix=rs.get("start_unix"),
                           end_unix=_end_unix(rs),
                           duration_ms=rs.get("duration_ms"),
                           trace_id=rs.get("trace_id"),
                           replay=(rs.get("attributes") or {})
                           .get("replay", False),
                           attempts=len(round_spans))
            if (r, h) in incarnations:
                row["incarnation"] = incarnations[(r, h)]
            host_rows[h] = row

        # -- critical-path attribution --------------------------------
        blocked_evicts = [rec for rec in evicts
                          if rec.get("blocked_round") == r]
        critical_host = critical_phase = None
        if blocked_evicts:
            critical_host = blocked_evicts[-1]["host"]
            critical_phase = "evicted"
        else:
            pub_end: Dict[str, float] = {}
            for h in members:
                pubs = _spans_of(r, h, ("publish",))
                if not pubs:
                    critical_host, critical_phase = h, "missing"
                    break
                pub_end[h] = max(_end_unix(s) for s in pubs)
            if critical_host is None and pub_end:
                critical_host = max(sorted(pub_end), key=pub_end.get)
                cands = _spans_of(r, critical_host, _ROUND_PHASES) + \
                    _spans_of(r - 1, critical_host, _PREV_TAIL_PHASES)
                critical_phase = (max(
                    cands, key=lambda s: s.get("duration_ms") or 0.0)
                    ["name"] if cands else "unattributed")

        events = [rec for rec in log
                  if rec.get("blocked_round") == r
                  or rec.get("effective_round") == r]
        entry = {"round": r, "members": members,
                 "critical_host": critical_host,
                 "critical_phase": critical_phase,
                 "hosts": host_rows}
        if reduce_rec is not None:
            entry["reduce_by"] = reduce_rec.get("by")
        if events:
            entry["events"] = events
        out_rounds.append(entry)

    trace_ids = sorted({s.get("trace_id")
                        for group in by_rh.values() for s in group
                        if s.get("trace_id")})
    return {"rounds": out_rounds, "hosts": hosts, "events": log,
            "trace_ids": trace_ids,
            "n_spans": sum(len(v) for v in by_rh.values())}


def render_fleet_text(tl: dict) -> str:
    lines = [f"fleet timeline: {len(tl['hosts'])} hosts "
             f"({', '.join(tl['hosts'])}), {len(tl['rounds'])} rounds, "
             f"traces: {', '.join(t[:12] for t in tl['trace_ids'])}"]
    for rd in tl["rounds"]:
        lines.append(
            f"round {rd['round']}: members={','.join(rd['members'])} "
            f"critical={rd['critical_host']} "
            f"phase={rd['critical_phase']}")
        for h, row in sorted(rd["hosts"].items()):
            phases = " ".join(f"{k}={v:.1f}ms"
                              for k, v in row["phases_ms"].items())
            extra = " REPLAY" if row.get("replay") else ""
            dur = row.get("duration_ms")
            dur_s = f" total={dur:.1f}ms" if dur is not None else ""
            lines.append(f"  {h}:{dur_s} {phases}{extra}")
        for ev in rd.get("events", ()):
            lines.append(
                f"  ! {ev['event']} {ev['host']} "
                f"effective_round={ev.get('effective_round')} "
                f"by={ev.get('by')} trace={str(ev.get('trace_id'))[:12]}")
    return "\n".join(lines)


# ----------------------------------------------------------------------
# per-trace / per-request timelines
# ----------------------------------------------------------------------

def _nest(spans: List[dict]) -> Tuple[List[dict], Dict[str, dict]]:
    """Parent-link nesting: returns (roots, node map). Roots are spans
    whose parent is absent from the set (a remote parent is a valid
    root locally)."""
    nodes = {s["span_id"]: {**s, "children": []} for s in spans}
    roots = []
    for s in spans:
        node = nodes[s["span_id"]]
        parent = nodes.get(s.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node["children"].sort(key=lambda n: n.get("start_unix") or 0.0)
    roots.sort(key=lambda n: n.get("start_unix") or 0.0)
    return roots, nodes


def _group_by_trace(spans_or_tracer, trace_id: Optional[str]
                    ) -> Dict[str, List[dict]]:
    """The one copy of the span intake both payload halves share:
    unwrap a Tracer, dict-ify, filter by trace id, group by trace."""
    spans = getattr(spans_or_tracer, "finished", spans_or_tracer)
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        d = _as_dict(s)
        if trace_id is not None and d.get("trace_id") != trace_id:
            continue
        by_trace.setdefault(d["trace_id"], []).append(d)
    return by_trace


def trace_summaries(spans_or_tracer,
                    trace_id: Optional[str] = None) -> List[dict]:
    """Group spans by trace and nest by parentage — the generic
    ``/debug/timeline`` payload. ``spans_or_tracer`` is a Tracer, an
    iterable of Spans, or an iterable of span dicts."""
    by_trace = _group_by_trace(spans_or_tracer, trace_id)
    out = []
    for tid, group in by_trace.items():
        group = _dedupe(group)
        roots, _nodes = _nest(group)
        out.append({"trace_id": tid, "n_spans": len(group),
                    "start_unix": min((s.get("start_unix") or 0.0)
                                      for s in group),
                    "spans": roots})
    out.sort(key=lambda t: t["start_unix"])
    return out


def request_timelines(spans_or_tracer, root_name: str = "decode.request",
                      trace_id: Optional[str] = None) -> List[dict]:
    """One nested timeline per served decode request: the request span
    (with the scheduler's TTFT decomposition in its attributes) plus its
    queue/prefill/block children, ordered by submit time. Selected by
    NAME anywhere in the trace tree — a request parented on a caller's
    span that lives in the same tracer is still a request."""
    by_trace = _group_by_trace(spans_or_tracer, trace_id)
    out = []
    for tid, group in by_trace.items():
        _roots, nodes = _nest(_dedupe(group))
        for node in nodes.values():
            if node["name"] != root_name:
                continue
            out.append({"trace_id": tid,
                        "start_unix": node.get("start_unix"),
                        "duration_ms": node.get("duration_ms"),
                        "attributes": node.get("attributes", {}),
                        "status": node.get("status"),
                        "spans": node})
    out.sort(key=lambda t: t.get("start_unix") or 0.0)
    return out


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.util.timeline",
        description="Merge elastic-fleet trace exports into one ordered "
                    "per-round attribution timeline, or render decode "
                    "request timelines from a tracer JSONL export.")
    p.add_argument("--store", help="coordination-store directory of the "
                                   "elastic run (FileCoordinationStore)")
    p.add_argument("--jsonl", nargs="*", default=[],
                   help="per-host tracer JSONL exports (globs ok)")
    p.add_argument("--requests", action="store_true",
                   help="render decode request timelines from --jsonl "
                        "instead of a fleet timeline")
    p.add_argument("--trace-id", help="restrict to one trace id")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit JSON instead of text")
    args = p.parse_args(argv)
    if not args.store and not args.jsonl:
        p.error("need --store and/or --jsonl")
    if args.requests:
        spans = [s for g in _expand_jsonl(args.jsonl)
                 for s in load_jsonl(g)]
        reqs = request_timelines(spans, trace_id=args.trace_id)
        if args.as_json:
            print(json.dumps(reqs, indent=2))
        else:
            for r in reqs:
                a = r["attributes"]
                print(f"request {r['trace_id'][:12]} "
                      f"dur={r['duration_ms']:.1f}ms "
                      f"tokens={a.get('tokens')} "
                      f"finish={a.get('finish_reason')} "
                      f"ttft_ms={a.get('ttft_ms')}")
        return 0
    tl = build_fleet_timeline(store=args.store, jsonl_paths=args.jsonl)
    print(json.dumps(tl, indent=2, default=repr) if args.as_json
          else render_fleet_text(tl))
    return 0


if __name__ == "__main__":
    sys.exit(main())
