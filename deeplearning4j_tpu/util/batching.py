"""Shared batch-iteration helper used by both network runtimes and the
early-stopping trainer (single source of truth for the DataSet / tuple /
iterator dispatch)."""

from __future__ import annotations

from typing import Iterator, Optional, Tuple


def iter_batches(data, labels=None, mask=None) -> Iterator[Tuple]:
    """Yield (features, labels, features_mask) triples.

    `data` may be: (features, labels[, mask]) arrays; a bare feature
    array with no labels (ONE unlabeled batch, labels None — the
    pretrain() call pattern); a DataSet (has .features/.labels); or an
    iterator yielding DataSets or tuples.
    """
    if labels is not None:
        yield (data, labels, mask)
        return
    if hasattr(data, "shape"):
        # bare feature array, no labels: ONE unlabeled batch (the
        # pretrain() call pattern) — iterating its rows is never meant
        yield (data, None, mask)
        return
    if hasattr(data, "features"):
        yield (data.features, data.labels,
               getattr(data, "features_mask", None))
        return
    # a 2/3-tuple of arrays — or of lists of arrays (multi-input graphs) —
    # is ONE batch, not an iterator of batches
    def _batchlike(a):
        if a is None or hasattr(a, "shape"):
            return True
        return (isinstance(a, list) and len(a) > 0
                and all(hasattr(e, "shape") or e is None for e in a))

    if (isinstance(data, tuple) and len(data) in (2, 3)
            and all(_batchlike(a) for a in data)):
        x, y = data[0], data[1]
        m = data[2] if len(data) > 2 else mask
        yield (x, y, m)
        return
    for item in data:
        if hasattr(item, "features"):
            yield (item.features, item.labels,
                   getattr(item, "features_mask", None))
        else:
            x, y = item[0], item[1]
            m = item[2] if len(item) > 2 else None
            yield (x, y, m)
