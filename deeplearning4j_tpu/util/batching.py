"""Shared batch-iteration helper used by both network runtimes and the
early-stopping trainer (single source of truth for the DataSet / tuple /
iterator dispatch)."""

from __future__ import annotations

from typing import Iterator, Optional, Tuple


def iter_batches(data, labels=None, mask=None,
                 with_meta: bool = False) -> Iterator[Tuple]:
    """Yield (features, labels, features_mask) triples — or, with
    ``with_meta=True``, (features, labels, features_mask, metadata)
    quadruples where metadata is the per-example ``RecordMetaData`` list a
    DataSet carries (``collect_metadata=True`` readers), else None.

    `data` may be: (features, labels[, mask]) arrays; a bare feature
    array with no labels (ONE unlabeled batch, labels None — the
    pretrain() call pattern); a DataSet (has .features/.labels); or an
    iterator yielding DataSets or tuples. ONE dispatch chain for every
    caller, so the eval-with-provenance path cannot drift from fit's.
    """
    def out(x, y, m, meta=None):
        return (x, y, m, meta) if with_meta else (x, y, m)

    def ds_out(ds):
        return out(ds.features, ds.labels,
                   getattr(ds, "features_mask", None),
                   getattr(ds, "example_metadata", None) or None)

    if labels is not None:
        yield out(data, labels, mask)
        return
    if hasattr(data, "shape"):
        # bare feature array, no labels: ONE unlabeled batch (the
        # pretrain() call pattern) — iterating its rows is never meant
        yield out(data, None, mask)
        return
    if hasattr(data, "features"):
        yield ds_out(data)
        return
    # a 2/3-tuple of arrays — or of lists of arrays (multi-input graphs) —
    # is ONE batch, not an iterator of batches
    def _batchlike(a):
        if a is None or hasattr(a, "shape"):
            return True
        return (isinstance(a, list) and len(a) > 0
                and all(hasattr(e, "shape") or e is None for e in a))

    if (isinstance(data, tuple) and len(data) in (2, 3)
            and all(_batchlike(a) for a in data)):
        x, y = data[0], data[1]
        m = data[2] if len(data) > 2 else mask
        yield out(x, y, m)
        return
    for item in data:
        if hasattr(item, "features"):
            yield ds_out(item)
        else:
            x, y = item[0], item[1]
            m = item[2] if len(item) > 2 else None
            yield out(x, y, m)
