"""Durable training: any ``fit()`` is killable at any step and resumable
bit-exactly.

On TPU pods preemption is routine; the reference's answer was Spark
lineage (SURVEY §5) — recompute lost partitions. A TPU-native trainer
cannot recompute device state, so the framework owns exact resume
instead, the way TF-Replicator-style frameworks treat restartable
training state as table stakes (PAPERS.md):

- :class:`TrainingState` — a versioned snapshot of EVERYTHING a step
  depends on: params, updater state, layer state (BN stats), the
  iteration/epoch/update counters the RNG streams derive from
  (``rng.fold_name(key(seed), f"update_{n}")``), and the **data-source
  cursor** (the ``state()``/``restore()`` seekable protocol implemented
  by the in-tree array, ``MultipleEpochs``, DataVec record-reader and
  Async iterators, and the sharded-record input pipeline —
  ``data.pipeline.RecordDataSetIterator``, whose cursor carries shard
  position, record offset, shuffle-buffer refs + rng state AND the
  augmentation batch counter, so even random crop/flip draws replay
  bit-exactly). Restoring a snapshot replays zero batches and skips
  none.
- :class:`CheckpointStore` — multi-file snapshot directories committed
  atomically: files land in a ``.wip`` dir, a ``COMMIT`` marker with a
  sha256 manifest is written LAST, and only then does the directory
  rename into place. ``load_latest()`` validates marker + manifest +
  model artifact and falls back past any torn/partial commit, so a crash
  at any byte of a write never costs more than one checkpoint interval.
- :class:`AsyncCheckpointWriter` — a single-outstanding background
  writer. ``TrainingState.capture`` copies the pytrees ON DEVICE (an
  async dispatch, safe against the train step's buffer donation); the
  writer thread pays the device→host transfer, serialization and fsync
  off the critical path. ``checkpoint_write_seconds`` /
  ``checkpoint_commits_total`` land in the metrics registry.
- :class:`PreemptionHandler` — SIGTERM/SIGINT set a drain flag; the fit
  loop finishes the dispatched in-flight window, writes a final snapshot
  synchronously, and returns cleanly. A second signal aborts hard.
- :class:`StepWatchdog` — a no-progress deadline around dispatch/ingest.
  On expiry it dumps ingest queue depths, live circuit-breaker states and
  the active tracing span, then raises :class:`WatchdogTimeout` (and, for
  a truly hung dispatch, interrupts the main thread so the blocking call
  itself unwinds).
- :class:`DurableSession` / :class:`DurableTrainer` — the wiring into
  ``util.ingest.run_fit_loop`` (both network runtimes route through it)
  and the user-facing resume-on-construction trainer. On multi-process
  runs every host must agree on the step digest
  (``parallel.distributed.agree_on_digest``) before a commit publishes.

Chaos story: the fit loop exposes a ``"training.step"`` seam
(:mod:`deeplearning4j_tpu.util.faults`) hit once per dispatched step, so
tests script kills at EXACT step boundaries (raise, ``os._exit``, or
self-SIGTERM) — see ``tests/test_durable.py`` and the fork-and-kill
subprocess harness ``tests/_kill_harness.py`` (which also runs N-process
ELASTIC fleets with per-rank kill plans).

Elastic rejoin rides this module: each elastic host keeps its own
:class:`CheckpointStore` of round-boundary snapshots whose cursor
carries the ROUND index, so a preempted host restores the newest
snapshot and deterministically replays its missed rounds
(:mod:`deeplearning4j_tpu.parallel.elastic`); the :class:`StepWatchdog`
context provider carries the elastic round/waiting-on state into the
expiry dump.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import queue
import re
import shutil
import signal
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import numpy as np

from . import faults as _faults
from . import flightrecorder as _flight
from . import metrics as _metrics
from .resilience import SYSTEM_CLOCK, Clock
from .serialization import (CheckpointInvalid, ModelSerializer,
                            _write_file_atomic, load_model,
                            verify_checkpoint)

logger = logging.getLogger("deeplearning4j_tpu")

STATE_VERSION = 1

# Set by an expiring StepWatchdog just before it interrupts the main
# thread; consumed by PreemptionHandler._handle so the simulated SIGINT
# unwinds the hung dispatch (KeyboardInterrupt) instead of being absorbed
# as a graceful-drain request that a hung loop can never observe.
_WATCHDOG_INTERRUPT = threading.Event()

_MODEL_ENTRY = "model.zip"
_CURSOR_ENTRY = "cursor.json"
_COMMIT_ENTRY = "COMMIT"
_STATE_RE = re.compile(r"^state_epoch(\d+)_iter(\d+)$")


# ----------------------------------------------------------------------
# seekable protocol
# ----------------------------------------------------------------------

def is_seekable(source: Any) -> bool:
    """True when ``source`` implements the cursor protocol
    (``state() -> dict`` / ``restore(state)``) — required for exact
    mid-epoch resume. The in-tree dataset/datavec iterators and the Async
    wrappers all do. A source may veto via a ``seekable()`` method (the
    Async wrapper does, when its BASE has no cursor)."""
    probe = getattr(source, "seekable", None)
    if callable(probe):
        try:
            if not probe():
                return False
        except Exception:
            return False
    return (callable(getattr(source, "state", None))
            and callable(getattr(source, "restore", None)))


def mask_fit_kwargs(net, mask) -> dict:
    """Validate the optional ``mask`` kwarg against the runtime's fit
    signature (ComputationGraph.fit has none — masks ride in DataSet
    batches) and return it in kwargs form. Shared by the durable and
    recoverable trainers."""
    if mask is None:
        return {}
    import inspect
    if "mask" not in inspect.signature(net.fit).parameters:
        raise ValueError(
            "mask kwarg is only supported for MultiLayerNetwork; "
            "pass masks via DataSet batches for graphs")
    return {"mask": mask}


# ----------------------------------------------------------------------
# digests
# ----------------------------------------------------------------------

def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    """sha256 of a file in fixed-size chunks — a multi-GB model artifact
    must never be slurped into RAM just to hash it."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def params_digest(params, updater_state=None, update_count: int = 0) -> str:
    """sha256 over every leaf of the (host) param/updater pytrees in
    deterministic path order, plus the update counter — the value all
    hosts must agree on before a multi-process commit."""
    import jax
    h = hashlib.sha256()
    h.update(str(int(update_count)).encode())
    for tree in (params, updater_state):
        if tree is None:
            continue
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        h.update(str(treedef).encode())
        for leaf in leaves:
            a = np.asarray(leaf)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    return h.hexdigest()


def default_commit_gate() -> Callable[[str], bool]:
    """The pre-commit agreement check: single-process runs always pass;
    multi-process runs require every host to present the same digest."""
    def gate(digest: str) -> bool:
        import jax
        if jax.process_count() == 1:
            return True
        from ..parallel.distributed import agree_on_digest
        return agree_on_digest(digest)
    return gate


# ----------------------------------------------------------------------
# metric families
# ----------------------------------------------------------------------

_WRITE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                  5.0, 10.0, 30.0)


def _reg(registry=None) -> _metrics.MetricsRegistry:
    return registry if registry is not None else _metrics.REGISTRY


def write_seconds_histogram(registry=None) -> _metrics.Histogram:
    return _reg(registry).histogram(
        "checkpoint_write_seconds",
        "Wall time of one TrainingState write (device_get + serialize + "
        "fsync + commit), off the critical path", buckets=_WRITE_BUCKETS)


def commits_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "checkpoint_commits_total",
        "TrainingState snapshots committed (COMMIT marker renamed into "
        "place)", ("kind",))


def skipped_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "checkpoint_writes_skipped_total",
        "Snapshot submissions dropped because a write was already "
        "outstanding (single-outstanding writer)")


def failures_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "checkpoint_write_failures_total",
        "TrainingState writes that raised (training continues; the "
        "previous valid snapshot remains the recovery point)")


# ----------------------------------------------------------------------
# TrainingState
# ----------------------------------------------------------------------

class TrainingState:
    """One resumable instant of a training run.

    ``capture()`` copies the param/updater/layer-state pytrees ON DEVICE
    (``jnp.array`` — an async device-to-device copy), because the jitted
    train step DONATES the live buffers: by the time a background writer
    looks at them the originals are invalid. The host transfer happens in
    ``write_to()`` on whatever thread runs it.
    """

    __slots__ = ("model_class", "conf", "params", "layer_state",
                 "updater_state", "iteration_count", "epoch_count",
                 "update_count", "seed", "cursor", "kind")

    def __init__(self, *, model_class, conf, params, layer_state,
                 updater_state, iteration_count, epoch_count, update_count,
                 seed, cursor, kind="step"):
        self.model_class = model_class
        self.conf = conf
        self.params = params
        self.layer_state = layer_state
        self.updater_state = updater_state
        self.iteration_count = int(iteration_count)
        self.epoch_count = int(epoch_count)
        self.update_count = int(update_count)
        self.seed = seed
        self.cursor = cursor
        self.kind = kind

    @classmethod
    def capture(cls, net, *, cursor: Optional[dict] = None,
                kind: str = "step") -> "TrainingState":
        import jax
        import jax.numpy as jnp

        def copy(tree):
            return jax.tree_util.tree_map(
                lambda a: jnp.array(a) if isinstance(a, jax.Array) else a,
                tree)

        return cls(
            model_class=type(net).__name__, conf=net.conf,
            params=copy(net.params), layer_state=copy(net.state),
            updater_state=copy(net.updater_state),
            iteration_count=getattr(net, "iteration_count", 0),
            epoch_count=getattr(net, "epoch_count", 0),
            update_count=getattr(net, "_update_count", 0),
            seed=getattr(net.training, "seed", 0), cursor=cursor, kind=kind)

    @property
    def name(self) -> str:
        return f"state_epoch{self.epoch_count}_iter{self.iteration_count}"

    def _shim(self):
        """Duck-typed stand-in ``ModelSerializer.write_model`` accepts."""
        class _Snapshot:
            pass
        s = _Snapshot()
        s.conf = self.conf
        s.params = self.params
        s.state = self.layer_state
        s.updater_state = self.updater_state
        s.iteration_count = self.iteration_count
        s.epoch_count = self.epoch_count
        s._update_count = self.update_count
        return s

    def digest(self) -> str:
        import jax
        return params_digest(jax.device_get(self.params),
                             jax.device_get(self.updater_state),
                             self.update_count)


class LoadedState(NamedTuple):
    net: Any
    cursor: Optional[dict]
    epoch_count: int
    iteration_count: int
    update_count: int
    digest: str
    path: str


# ----------------------------------------------------------------------
# CheckpointStore: the atomic multi-file commit protocol
# ----------------------------------------------------------------------

class CheckpointStore:
    """Rolling TrainingState snapshots in one directory (single writer).

    Commit protocol: every file of a snapshot (``model.zip``,
    ``cursor.json``) is written inside a ``.wipstate_*`` staging dir; the
    ``COMMIT`` marker — a sha256 manifest of the other files — is written
    last; only then does the staging dir rename to its final
    ``state_epoch{E}_iter{I}`` name. A reader therefore never sees a torn
    multi-file state: either the rename happened (and the manifest proves
    every file complete) or the snapshot does not exist. Stale staging
    dirs from crashed writers are swept on construction.
    """

    def __init__(self, directory: str, keep: int = 2):
        self.directory = directory
        self.keep = max(1, int(keep))
        os.makedirs(directory, exist_ok=True)
        for name in os.listdir(directory):
            if name.startswith(".wipstate_"):
                shutil.rmtree(os.path.join(directory, name),
                              ignore_errors=True)

    # -- enumeration ---------------------------------------------------

    def snapshots(self) -> List[str]:
        out = [n for n in os.listdir(self.directory) if _STATE_RE.match(n)]
        out.sort(key=lambda n: tuple(map(int, _STATE_RE.match(n).groups())))
        return out

    def latest_valid(self) -> Optional[str]:
        for name in reversed(self.snapshots()):
            path = os.path.join(self.directory, name)
            try:
                self.validate(path)
                return path
            except CheckpointInvalid as e:
                logger.warning(
                    "skipping torn/invalid snapshot %s (%s) — falling "
                    "back to the previous one", path, e)
        return None

    # -- validation ----------------------------------------------------

    def validate(self, path: str) -> None:
        """Raise :class:`CheckpointInvalid` unless ``path`` is a fully
        committed, manifest-verified snapshot."""
        commit = os.path.join(path, _COMMIT_ENTRY)
        try:
            with open(commit, "r") as f:
                marker = json.load(f)
        except FileNotFoundError:
            raise CheckpointInvalid(f"{path}: no COMMIT marker "
                                    "(torn or in-progress write)")
        except Exception as e:
            raise CheckpointInvalid(
                f"{path}: unreadable COMMIT marker ({e})")
        if marker.get("version") != STATE_VERSION:
            raise CheckpointInvalid(
                f"{path}: unsupported state version "
                f"{marker.get('version')!r}")
        manifest = marker.get("manifest", {})
        for entry in (_MODEL_ENTRY, _CURSOR_ENTRY):
            if entry not in manifest:
                raise CheckpointInvalid(
                    f"{path}: COMMIT manifest missing {entry!r}")
        for entry, want in manifest.items():
            fp = os.path.join(path, entry)
            try:
                got = _sha256_file(fp)
            except FileNotFoundError:
                raise CheckpointInvalid(
                    f"{path}: manifest names missing file {entry!r}")
            if got != want:
                raise CheckpointInvalid(
                    f"{path}: sha256 mismatch for {entry!r}")
        verify_checkpoint(os.path.join(path, _MODEL_ENTRY))

    # -- write ---------------------------------------------------------

    def save(self, state: TrainingState, *,
             commit_gate: Optional[Callable[[str], bool]] = None,
             registry=None) -> Optional[str]:
        """Serialize, manifest, gate, commit. Returns the committed path,
        or None when the commit gate refused (host digest disagreement)."""
        t0 = time.perf_counter()
        final = os.path.join(self.directory, state.name)
        if os.path.isdir(final):
            return final            # same step already committed
        wip = os.path.join(self.directory,
                           f".wipstate_{os.getpid()}_{state.name}")
        shutil.rmtree(wip, ignore_errors=True)
        os.makedirs(wip)
        try:
            import jax
            # host transfer happens HERE, on the writing thread
            host_params = jax.device_get(state.params)
            host_updater = jax.device_get(state.updater_state)
            digest = params_digest(host_params, host_updater,
                                   state.update_count)
            model_path = os.path.join(wip, _MODEL_ENTRY)
            ModelSerializer.write_model(state._shim(), model_path,
                                        save_updater=True,
                                        model_class=state.model_class)
            cursor_doc = {
                "version": STATE_VERSION,
                "kind": state.kind,
                "model_class": state.model_class,
                "epoch_count": state.epoch_count,
                "iteration_count": state.iteration_count,
                "update_count": state.update_count,
                "cursor": state.cursor,
                "rng": {"seed": state.seed,
                        "update_count": state.update_count},
                "digest": digest,
            }
            cursor_path = os.path.join(wip, _CURSOR_ENTRY)
            _write_file_atomic(cursor_path,
                               json.dumps(cursor_doc, indent=2).encode())
            manifest = {}
            for entry in (_MODEL_ENTRY, _CURSOR_ENTRY):
                manifest[entry] = _sha256_file(os.path.join(wip, entry))
            gate = commit_gate
            if gate is not None and not gate(digest):
                logger.error(
                    "checkpoint %s NOT committed: hosts disagree on the "
                    "step digest — refusing to publish a diverged state",
                    state.name)
                return None
            # COMMIT marker last: its presence asserts every prior byte
            _write_file_atomic(
                os.path.join(wip, _COMMIT_ENTRY),
                json.dumps({"version": STATE_VERSION,
                            "manifest": manifest}, indent=2).encode())
            os.rename(wip, final)
        finally:
            shutil.rmtree(wip, ignore_errors=True)
        commits_counter(registry).inc(kind=state.kind)
        dt = time.perf_counter() - t0
        write_seconds_histogram(registry).observe(dt)
        _flight.record("checkpoint_commit", name=state.name,
                       snapshot_kind=state.kind,
                       write_seconds=round(dt, 4))
        for stale in self.snapshots()[:-self.keep]:
            shutil.rmtree(os.path.join(self.directory, stale),
                          ignore_errors=True)
        return final

    # -- read ----------------------------------------------------------

    def load(self, path: str) -> LoadedState:
        self.validate(path)
        _faults.check("recovery.restore", {"path": path})
        with open(os.path.join(path, _CURSOR_ENTRY), "r") as f:
            doc = json.load(f)
        net = load_model(os.path.join(path, _MODEL_ENTRY),
                         load_updater=True)
        return LoadedState(
            net=net, cursor=doc.get("cursor"),
            epoch_count=int(doc.get("epoch_count", 0)),
            iteration_count=int(doc.get("iteration_count", 0)),
            update_count=int(doc.get("update_count", 0)),
            digest=doc.get("digest", ""), path=path)

    def load_latest(self) -> Optional[LoadedState]:
        """Newest snapshot that validates AND loads; torn commits and
        corrupt artifacts fall back to the previous one."""
        for name in reversed(self.snapshots()):
            path = os.path.join(self.directory, name)
            try:
                return self.load(path)
            except Exception as e:
                logger.warning(
                    "snapshot %s unusable (%s: %s) — falling back to the "
                    "previous one", path, type(e).__name__, e)
        return None


# ----------------------------------------------------------------------
# AsyncCheckpointWriter
# ----------------------------------------------------------------------

class AsyncCheckpointWriter:
    """Single-outstanding background snapshot writer.

    ``submit(state)`` hands one captured :class:`TrainingState` to the
    writer thread and returns immediately; while a write is queued or in
    progress further submissions return False (and count into
    ``checkpoint_writes_skipped_total``) — checkpointing never queues up
    behind a slow filesystem. Write errors are logged and counted, never
    raised into the training loop; the previous valid snapshot remains
    the recovery point.

    Multi-process caveat: the commit gate is a COLLECTIVE
    (``process_allgather``), so the busy-skip must not be a host-local
    decision — one slow host skipping while the others enter the
    collective would hang them. With a gate on a multi-process run,
    ``submit`` therefore WAITS for the outstanding write instead of
    skipping, keeping every host's attempt count identical.
    """

    def __init__(self, store: CheckpointStore, *,
                 commit_gate: Optional[Callable[[str], bool]] = None,
                 registry=None, collective: Optional[bool] = None):
        self.store = store
        self.commit_gate = commit_gate
        self.registry = registry
        if collective is None:
            import jax
            collective = commit_gate is not None and jax.process_count() > 1
        self.collective = collective
        self.last_error: Optional[BaseException] = None
        self.last_path: Optional[str] = None
        self._q: "queue.Queue" = queue.Queue()
        self._busy = False
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._closed = False
        self._thread = threading.Thread(target=self._worker,
                                        name="ckpt-writer", daemon=True)
        self._thread.start()

    def would_drop(self) -> bool:
        """True when ``submit()`` would busy-skip right now — callers can
        avoid paying ``TrainingState.capture`` (a whole-model device
        copy) for a snapshot that would be dropped. Counts the skip."""
        with self._lock:
            busy = self._busy and not self._closed
        if busy and not self.collective:
            skipped_counter(self.registry).inc()
            return True
        return False

    def submit(self, state: TrainingState) -> bool:
        while True:
            with self._lock:
                if self._closed:
                    raise RuntimeError("writer is closed")
                if not self._busy:
                    self._busy = True
                    break
            if not self.collective:
                skipped_counter(self.registry).inc()
                return False
            # every host must attempt every checkpoint (collective gate)
            if not self.drain(60.0):
                logger.warning(
                    "checkpoint write still outstanding after 60s — "
                    "waiting (collective commit gate forbids skipping)")
        self._q.put(state)
        return True

    def _worker(self) -> None:
        while True:
            state = self._q.get()
            if state is None:
                return
            try:
                self.last_path = self.store.save(
                    state, commit_gate=self.commit_gate,
                    registry=self.registry)
            except BaseException as e:
                self.last_error = e
                failures_counter(self.registry).inc()
                logger.error(
                    "async checkpoint write failed (%s: %s) — training "
                    "continues from the previous valid snapshot",
                    type(e).__name__, e)
            finally:
                with self._idle:
                    self._busy = False
                    self._idle.notify_all()

    def drain(self, timeout: float = 60.0) -> bool:
        """Wait for the in-flight write (if any) to finish."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def close(self, timeout: float = 60.0) -> None:
        self.drain(timeout)
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._q.put(None)
        self._thread.join(timeout=timeout)


# ----------------------------------------------------------------------
# StepWatchdog
# ----------------------------------------------------------------------

class WatchdogTimeout(RuntimeError):
    """No training progress within the deadline. ``dump`` carries the
    diagnostic snapshot taken at expiry."""

    def __init__(self, msg: str, dump: Optional[dict] = None):
        super().__init__(msg)
        self.dump = dump or {}


class StepWatchdog:
    """No-progress deadline around the dispatch/ingest loop.

    The fit loop calls ``pet()`` once per dispatched step (which also
    captures the active tracing span via the faults seam-context
    providers). If no pet arrives within ``deadline_s``, the watchdog
    builds a diagnostic dump — elapsed time, ingest queue depths, live
    circuit-breaker states, the span active at the last pet — logs it,
    and raises :class:`WatchdogTimeout` at the next ``pet()``/``check()``.
    With the monitor thread enabled (default when armed against the real
    clock) it ALSO interrupts the main thread, so a dispatch hung inside
    ``block_until_ready`` unwinds instead of hanging forever.
    """

    def __init__(self, deadline_s: float, *, clock: Clock = SYSTEM_CLOCK,
                 registry=None,
                 context_provider: Optional[Callable[[], dict]] = None,
                 on_timeout: Optional[Callable[[dict], None]] = None,
                 interrupt_main: bool = True,
                 poll_interval_s: Optional[float] = None,
                 thread: Optional[bool] = None):
        self.deadline_s = float(deadline_s)
        self.clock = clock
        self.registry = registry
        self.context_provider = (context_provider if context_provider
                                 is not None else _faults.seam_context)
        self.on_timeout = on_timeout
        self.interrupt_main = interrupt_main
        self.poll_interval_s = (poll_interval_s if poll_interval_s
                                is not None else max(0.05,
                                                     self.deadline_s / 4))
        # monitor thread defaults ON against the real clock (a hung
        # dispatch never calls pet() again, so only a thread can notice);
        # a test-injected manual clock advances synchronously, so expiry
        # is evaluated in pet()/check() instead
        self._use_thread = (clock is SYSTEM_CLOCK if thread is None
                            else thread)
        self._lock = threading.Lock()
        self._last: Optional[float] = None       # None = disarmed
        self._last_context: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_dump: Optional[dict] = None
        self._raised = False
        self._expiring = False

    # -- lifecycle -----------------------------------------------------

    def arm(self) -> None:
        with self._lock:
            self._last = self.clock.monotonic()
            self.last_dump = None
            self._raised = False
            self._expiring = False
        if self._use_thread and (self._thread is None
                                 or not self._thread.is_alive()):
            self._stop.clear()
            self._thread = threading.Thread(target=self._monitor,
                                            name="step-watchdog",
                                            daemon=True)
            self._thread.start()

    def disarm(self) -> None:
        with self._lock:
            self._last = None
        _WATCHDOG_INTERRUPT.clear()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "StepWatchdog":
        self.arm()
        return self

    def __exit__(self, *exc) -> bool:
        self.disarm()
        return False

    # -- progress ------------------------------------------------------

    def pet(self) -> None:
        """Mark progress (raises first if the deadline already expired)."""
        self.check()
        ctx = {}
        try:
            ctx = self.context_provider() or {}
        except Exception:
            pass
        with self._lock:
            self._last = self.clock.monotonic()
            self._last_context = ctx

    def check(self) -> None:
        with self._lock:
            expired = (self._last is not None and not self._raised
                       and self.clock.monotonic() - self._last
                       >= self.deadline_s)
        if expired:
            self._expire()
        if self.last_dump is not None and not self._raised:
            self._raised = True
            raise WatchdogTimeout(
                f"no training progress for >= {self.deadline_s:.1f}s",
                self.last_dump)

    # -- expiry --------------------------------------------------------

    def dump(self) -> dict:
        """The diagnostic snapshot: elapsed, ingest queue depths, breaker
        states, and the span active at the last progress mark."""
        from . import resilience as _resilience
        with self._lock:
            elapsed = (None if self._last is None
                       else self.clock.monotonic() - self._last)
            ctx = dict(self._last_context)
        queues = {}
        g = _reg(self.registry).get("ingest_queue_depth")
        if g is not None:
            try:
                for s in g.snapshot().get("series", []):
                    queues[s["labels"].get("stage", "?")] = s["value"]
            except Exception:
                pass
        return {"deadline_s": self.deadline_s, "elapsed_s": elapsed,
                "queue_depths": queues,
                "breakers": _resilience.breaker_states(),
                "active_span": ctx.get("span"),
                # elastic fleets stamp {host, round, phase, waiting_on}
                # via their context provider, so a watchdog expiry names
                # the peer that stalled the sync round without reading
                # the flight-recorder dump
                "elastic": ctx.get("elastic"),
                "context": ctx}

    def _expire(self) -> None:
        # claim the expiry under the lock: the monitor thread and a
        # main-thread check() racing here must not both fire the
        # interrupt/on_timeout action
        with self._lock:
            if self._expiring or self.last_dump is not None:
                return
            self._expiring = True
        d = self.dump()
        self.last_dump = d
        logger.error(
            "step watchdog expired after %.1fs without progress — queue "
            "depths: %s, breakers: %s, active span: %s",
            self.deadline_s, d["queue_depths"], d["breakers"],
            d["active_span"])
        # the black-box path: a hung dispatch may never unwind, so the
        # ring is written to disk HERE, before any interrupt/raise — the
        # last train_step event in the dump names the step that hung
        _flight.record("watchdog_expired", deadline_s=self.deadline_s,
                       elapsed_s=d["elapsed_s"],
                       queue_depths=d["queue_depths"],
                       breakers=d["breakers"],
                       active_span=d["active_span"])
        _flight.dump(reason="watchdog_expired")
        if self.on_timeout is not None:
            try:
                self.on_timeout(d)
            except Exception:
                logger.exception("watchdog on_timeout hook failed")
        elif (self.interrupt_main and self._use_thread
              and threading.current_thread()
              is not threading.main_thread()):
            # monitor-thread expiry: interrupt the (possibly hung) main
            # thread. Synchronous expiry via pet()/check() skips this —
            # the caller's own raise unwinds, and a self-interrupt would
            # leave a stray KeyboardInterrupt pending for cleanup code.
            # A REAL signal (os.kill), not _thread.interrupt_main():
            # interrupt_main only sets the pending flag, which a main
            # thread blocked inside a C call (a hung device dispatch,
            # time.sleep) never reaches — the kernel-delivered SIGINT
            # EINTRs the blocking call so the handler actually runs
            # (pinned by the fork-and-kill hang test)
            _WATCHDOG_INTERRUPT.set()
            try:
                os.kill(os.getpid(), signal.SIGINT)
            except Exception:
                import _thread
                _thread.interrupt_main()

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            with self._lock:
                expired = (self._last is not None
                           and self.clock.monotonic() - self._last
                           >= self.deadline_s)
            if expired:
                self._expire()
                return


# ----------------------------------------------------------------------
# PreemptionHandler
# ----------------------------------------------------------------------

class PreemptionHandler:
    """SIGTERM/SIGINT → graceful drain flag.

    The first signal sets ``requested``; the fit loop notices at the next
    step boundary, drains the in-flight window, writes a final snapshot
    and returns. A second signal raises ``KeyboardInterrupt`` immediately
    (the operator insisting). Install is a no-op off the main thread
    (Python only delivers signals there).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._previous: Dict[int, Any] = {}
        self.installed = False

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        """Programmatic preemption (tests; cluster agents that learn of
        preemption out-of-band)."""
        self._event.set()

    def _handle(self, signum, frame) -> None:
        if _WATCHDOG_INTERRUPT.is_set():
            # not the operator: an expired StepWatchdog interrupting a
            # hung dispatch — unwind it, don't absorb it as a drain flag
            _WATCHDOG_INTERRUPT.clear()
            raise KeyboardInterrupt(
                "step watchdog expired — unwinding hung dispatch")
        if self._event.is_set():
            _flight.record("preemption_abort", signum=int(signum))
            _flight.dump(reason="second_signal")
            raise KeyboardInterrupt(
                f"second signal {signum} during drain — aborting")
        logger.warning(
            "signal %d: draining in-flight work and writing a final "
            "checkpoint (send again to abort)", signum)
        # black-box the preemption instant: if the drain itself wedges or
        # the enclosing job is hard-killed mid-drain, the dump already
        # names the last dispatched step
        _flight.record("preemption_signal", signum=int(signum))
        _flight.dump(reason="preemption")
        self._event.set()

    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            return self
        _WATCHDOG_INTERRUPT.clear()
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handle)
        self.installed = True
        return self

    def uninstall(self) -> None:
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False


# ----------------------------------------------------------------------
# DurableSession: the run_fit_loop wiring
# ----------------------------------------------------------------------

class DurableSession:
    """Per-``fit()`` glue between the dispatch loop and the durable
    machinery. ``run_fit_loop`` calls :meth:`tap` around the batch
    source (BEFORE ingest staging, so cursors are recorded in production
    order), :meth:`on_step` after every dispatched step, and
    :meth:`on_epoch_boundary` after each completed epoch.
    """

    def __init__(self, net, store: Optional[CheckpointStore] = None, *,
                 data=None, frequency: int = 100,
                 writer: Optional[AsyncCheckpointWriter] = None,
                 watchdog: Optional[StepWatchdog] = None,
                 preemption: Optional[PreemptionHandler] = None,
                 max_steps: Optional[int] = None,
                 commit_gate: Optional[Callable[[str], bool]] = None,
                 resuming: bool = False, registry=None):
        if store is None and writer is not None:
            store = writer.store
        self.net = net
        self.store = store
        self.writer = writer
        self.frequency = max(1, int(frequency))
        self.watchdog = watchdog
        self.preemption = preemption
        self.max_steps = max_steps
        self.commit_gate = commit_gate
        self.registry = registry
        self.data = data
        self.seekable = data is not None and is_seekable(data)
        # True for the first epoch after a cursor restore: run_fit_loop
        # must not "revive" an exhausted iterator then — a cursor at the
        # exact end of an epoch means zero batches remain, not restart
        self.resuming = resuming
        self.stopped = False
        self.stop_reason: Optional[str] = None
        self.steps = 0
        self._in_partial_epoch = False
        self._cursors: collections.deque = collections.deque()
        self._cursor: Optional[dict] = None
        # cadence anchor: set from the first observed iteration_count so
        # a resumed run doesn't immediately re-checkpoint
        self._last_cp_iter: Optional[int] = None

    # -- cursor tap ----------------------------------------------------

    def tap(self, batches, data=None):
        """Wrap the batch iterable so each produced batch's post-read
        cursor is recorded (in production order — consumption order is
        identical, so the k-th ``on_step`` pop is the k-th batch's
        cursor). Pass-through for non-seekable sources. ``data`` rebinds
        the cursor source to the iterator the fit loop actually runs
        over (they can differ from the construction-time one)."""
        if data is not None and data is not self.data:
            self.data = data
            self.seekable = is_seekable(data)
        if not self.seekable:
            return batches
        source = self.data

        def gen():
            for b in batches:
                self._cursors.append(source.state())
                yield b
        return gen()

    # -- step/epoch hooks ----------------------------------------------

    def on_step(self, net, n_consumed: int = 1) -> bool:
        """Bookkeeping after one dispatched step (which consumed
        ``n_consumed`` source batches). Returns False when the loop must
        stop cleanly (preemption, max_steps) — the caller drains the
        in-flight window and returns."""
        for _ in range(n_consumed):
            if self._cursors:
                self._cursor = self._cursors.popleft()
        self.steps += n_consumed
        self._in_partial_epoch = True
        if self.watchdog is not None:
            self.watchdog.pet()
        it = getattr(net, "iteration_count", self.steps)
        if self._last_cp_iter is None:
            self._last_cp_iter = it - n_consumed
        # mid-epoch snapshots only when the cursor makes them EXACTLY
        # resumable; non-seekable sources get epoch boundaries only.
        # Crossing test, not divisibility: a coalesced scan advances the
        # counter by k per step, which can stride over every multiple
        if (self.seekable and (self.writer or self.store) is not None
                and it // self.frequency > self._last_cp_iter // self.frequency):
            self._last_cp_iter = it
            if self.writer is not None:
                if not self.writer.would_drop():
                    self.writer.submit(TrainingState.capture(
                        net, cursor=self._cursor, kind="step"))
            else:           # sync mode: deterministic, on the step path
                self.store.save(
                    TrainingState.capture(net, cursor=self._cursor,
                                          kind="step"),
                    commit_gate=self.commit_gate, registry=self.registry)
        if self.preemption is not None and self.preemption.requested:
            self.stopped, self.stop_reason = True, "preempted"
            return False
        if self.max_steps is not None and self.steps >= self.max_steps:
            self.stopped, self.stop_reason = True, "max_steps"
            return False
        return True

    def on_epoch_boundary(self, net) -> None:
        """Called after ``epoch_count`` incremented: an epoch-boundary
        snapshot (cursor None = start of the next epoch), and stale
        read-ahead cursors from the finished epoch are dropped."""
        self._cursors.clear()
        self._cursor = None
        self._in_partial_epoch = False
        if self.watchdog is not None:
            self.watchdog.pet()
        if (self.writer or self.store) is None:
            return          # store-less streaming session: nothing to
                            # snapshot INTO — skip the device copies
        if self.writer is not None:
            if not self.writer.would_drop():
                self.writer.submit(TrainingState.capture(
                    net, cursor=None, kind="boundary"))
        else:
            self.store.save(
                TrainingState.capture(net, cursor=None, kind="boundary"),
                commit_gate=self.commit_gate, registry=self.registry)

    # -- final snapshot ------------------------------------------------

    def final_snapshot(self, net) -> Optional[str]:
        """Synchronous write of the exact stop instant (after the
        in-flight window drained). Used on preemption."""
        if self.store is None:
            return None
        if self.writer is not None:
            self.writer.drain()
        if self._in_partial_epoch and not self.seekable:
            # a mid-epoch snapshot WITHOUT a cursor would be newer than
            # the last boundary snapshot but impossible to resume
            # exactly — the restarted epoch would re-apply its first
            # batches on top of the partial updates. Keep the boundary
            # snapshot as the recovery point instead.
            logger.warning(
                "preempted mid-epoch over a non-seekable data source — "
                "not writing a mid-epoch snapshot (exact resume needs "
                "state()/restore()); the last epoch-boundary snapshot "
                "remains the recovery point")
            return None
        state = TrainingState.capture(
            net, cursor=self._cursor if self.seekable else None,
            kind="final")
        return self.store.save(state, commit_gate=self.commit_gate,
                               registry=self.registry)


# ----------------------------------------------------------------------
# DurableTrainer: resume-on-construction fit
# ----------------------------------------------------------------------

class DurableTrainer:
    """``fit()`` that is killable at any step and resumes bit-exactly.

    Construction restores the newest valid :class:`TrainingState` from
    ``directory`` (falling back past torn commits). ``fit(data,
    epochs=N)`` trains until N TOTAL epochs are recorded, checkpointing
    asynchronously every ``frequency`` iterations (with the data-source
    cursor when the source is seekable) and at every epoch boundary;
    SIGTERM/SIGINT drain the in-flight window, write a final snapshot and
    return cleanly (``preempted`` is then True). An optional step
    watchdog bounds no-progress time.
    """

    def __init__(self, net, directory: str, *, frequency: int = 100,
                 keep: int = 2, async_writes: bool = True,
                 watchdog_s: Optional[float] = None,
                 handle_signals: bool = True,
                 commit_gate: Optional[Callable[[str], bool]] = "default",
                 registry=None):
        self.store = CheckpointStore(directory, keep=keep)
        # a durable run is exactly the kind whose crash needs a black
        # box: any unhandled exception dumps the flight recorder
        _flight.install_excepthook()
        self.frequency = max(1, int(frequency))
        self.async_writes = async_writes
        self.watchdog_s = watchdog_s
        self.handle_signals = handle_signals
        self.registry = registry
        self.commit_gate = (default_commit_gate()
                            if commit_gate == "default" else commit_gate)
        loaded = self.store.load_latest()
        self.resumed = loaded is not None
        self.net = loaded.net if loaded is not None else net
        self._resume_cursor = loaded.cursor if loaded is not None else None
        self.preempted = False
        self.session: Optional[DurableSession] = None

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None,
            coalesce: Optional[int] = None):
        """Train until ``epochs`` TOTAL epochs are recorded on the model.
        A run resumed mid-epoch continues that epoch from the restored
        cursor — replaying zero batches and skipping none — which
        requires the data source to be seekable."""
        net = self.net
        if net.params is None:
            net.init()
        resuming_mid_epoch = self._resume_cursor is not None
        if resuming_mid_epoch:
            if not is_seekable(data):
                raise ValueError(
                    "resuming a mid-epoch snapshot needs a seekable data "
                    "source (state()/restore()) — got "
                    f"{type(data).__name__}; pass the same seekable "
                    "iterator the interrupted run used")
            data.restore(self._resume_cursor)
            self._resume_cursor = None
        writer = (AsyncCheckpointWriter(self.store,
                                        commit_gate=self.commit_gate,
                                        registry=self.registry)
                  if self.async_writes else None)
        watchdog = (StepWatchdog(self.watchdog_s, registry=self.registry,
                                 thread=True)
                    if self.watchdog_s else None)
        preemption = PreemptionHandler() if self.handle_signals else None
        session = DurableSession(
            net, self.store, data=data, frequency=self.frequency,
            writer=writer, watchdog=watchdog, preemption=preemption,
            commit_gate=self.commit_gate, resuming=resuming_mid_epoch,
            registry=self.registry)
        self.session = session
        kwargs = {"session": session}
        if coalesce is not None:
            kwargs["coalesce"] = coalesce
        kwargs.update(mask_fit_kwargs(net, mask))
        if preemption is not None:
            preemption.install()
        if watchdog is not None:
            watchdog.arm()
        try:
            remaining = epochs - net.epoch_count
            if remaining > 0:
                try:
                    net.fit(data, labels, epochs=remaining, **kwargs)
                except KeyboardInterrupt:
                    if watchdog is not None and watchdog.last_dump:
                        raise WatchdogTimeout(
                            f"no training progress for >= "
                            f"{self.watchdog_s:.1f}s", watchdog.last_dump
                        ) from None
                    raise
            if session.stopped and session.stop_reason == "preempted":
                self.preempted = True
            if remaining > 0:
                # preempted: the exact stop instant (with cursor); clean
                # finish: the last epoch boundary, in case the async
                # writer was busy when it fired (same-name saves dedup)
                session.final_snapshot(net)
        finally:
            if watchdog is not None:
                watchdog.disarm()
            if preemption is not None:
                preemption.uninstall()
            if writer is not None:
                writer.close()
        return net
