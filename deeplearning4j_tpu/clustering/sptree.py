"""SpTree / QuadTree: Barnes-Hut space-partitioning trees.

Parity: reference ``clustering/sptree/SpTree.java`` (k-dimensional,
center-of-mass nodes, ``computeNonEdgeForces`` with the theta criterion,
``computeEdgeForces`` over sparse similarities) and
``clustering/quadtree/QuadTree.java`` (the 2-D special case).

This is the pure-Python reference implementation — the correctness oracle
for the C++ kernel (:mod:`.native`) that BarnesHutTsne actually uses at
scale. Array-based (no per-node objects): children/centers/masses live in
preallocated numpy arrays.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class SpTree:
    """k-d Barnes-Hut tree over points [n, d] (parity: ``SpTree.java``)."""

    def __init__(self, points: np.ndarray, capacity_mult: int = 4):
        points = np.asarray(points, dtype=np.float64)
        n, d = points.shape
        self.points = points
        self.n, self.d = n, d
        self.n_children = 1 << d
        max_nodes = max(4 * n + 64, 64)
        self._center = np.zeros((max_nodes, d))       # cell geometric center
        self._width = np.zeros((max_nodes, d))        # cell half-width
        self._com = np.zeros((max_nodes, d))          # center of mass
        self._count = np.zeros(max_nodes, dtype=np.int64)
        self._point = np.full(max_nodes, -1, dtype=np.int64)  # leaf payload
        self._children = np.full((max_nodes, self.n_children), -1,
                                 dtype=np.int64)
        self._n_nodes = 1
        lo, hi = points.min(axis=0), points.max(axis=0)
        mid = (lo + hi) / 2.0
        half = np.maximum((hi - lo) / 2.0, 1e-10) * 1.0000001
        self._center[0] = mid
        self._width[0] = half
        for i in range(n):
            self._insert(0, i)
        # per-node max half-width, computed once — recomputing this over the
        # whole preallocated array per query point is O(n^2) across a t-SNE
        # iteration (the C++ kernel keeps the same maxw[] cache)
        self._maxw = self._width[:self._n_nodes].max(axis=1)

    # ------------------------------------------------------------------

    def _child_index(self, node: int, p: np.ndarray) -> int:
        idx = 0
        for a in range(self.d):
            if p[a] > self._center[node, a]:
                idx |= (1 << a)
        return idx

    def _alloc_child(self, node: int, ci: int) -> int:
        new = self._n_nodes
        if new >= len(self._count):
            self._grow()
        self._n_nodes += 1
        half = self._width[node] / 2.0
        offs = np.array([half[a] if (ci >> a) & 1 else -half[a]
                         for a in range(self.d)])
        self._center[new] = self._center[node] + offs
        self._width[new] = half
        self._children[node, ci] = new
        return new

    def _grow(self) -> None:
        for name in ("_center", "_width", "_com"):
            arr = getattr(self, name)
            setattr(self, name, np.concatenate([arr, np.zeros_like(arr)]))
        self._count = np.concatenate([self._count,
                                      np.zeros_like(self._count)])
        self._point = np.concatenate([self._point,
                                      np.full_like(self._point, -1)])
        self._children = np.concatenate(
            [self._children, np.full_like(self._children, -1)])

    def _insert(self, node: int, i: int) -> None:
        p = self.points[i]
        while True:
            c = self._count[node]
            self._com[node] = (self._com[node] * c + p) / (c + 1)
            self._count[node] = c + 1
            if c == 0:                      # empty leaf: store point
                self._point[node] = i
                return
            if self._point[node] >= 0:      # occupied leaf: split
                j = self._point[node]
                if np.allclose(self.points[j], p, atol=1e-12):
                    return                  # duplicate point: mass only
                self._point[node] = -1
                cj = self._child_index(node, self.points[j])
                child = self._children[node, cj]
                if child < 0:
                    child = self._alloc_child(node, cj)
                # re-descend the displaced point into the subtree (its mass
                # above `node` is already accounted)
                self._insert(child, j)
            ci = self._child_index(node, p)
            child = self._children[node, ci]
            if child < 0:
                child = self._alloc_child(node, ci)
            node = child

    # ------------------------------------------------------------------

    def is_correct(self) -> bool:
        """Every point lies inside its cell (parity: SpTree.isCorrect)."""
        for node in range(self._n_nodes):
            i = self._point[node]
            if i < 0:
                continue
            p = self.points[i]
            if np.any(np.abs(p - self._center[node]) > self._width[node]):
                return False
        return True

    def depth(self) -> int:
        def _d(node):
            kids = [c for c in self._children[node] if c >= 0]
            return 1 + (max(_d(c) for c in kids) if kids else 0)
        return _d(0)

    def compute_non_edge_forces(self, i: int, theta: float
                                ) -> Tuple[np.ndarray, float]:
        """Repulsive force on point i via the theta criterion; returns
        (neg_force [d], sum_Q contribution) — parity:
        ``SpTree.computeNonEdgeForces``."""
        p = self.points[i]
        neg = np.zeros(self.d)
        sum_q = 0.0
        max_width = self._maxw
        stack = [0]
        while stack:
            node = stack.pop()
            cnt = self._count[node]
            if cnt == 0:
                continue
            if self._point[node] == i and cnt == 1:
                continue
            diff = p - self._com[node]
            d2 = float(diff @ diff)
            is_leaf = self._point[node] >= 0
            if is_leaf or (max_width[node] * max_width[node]
                           < theta * theta * d2):
                # single point, or far enough: treat cell as one mass
                cnt_eff = cnt - (1 if self._point[node] == i else 0)
                if cnt_eff <= 0:
                    continue
                q = 1.0 / (1.0 + d2)
                sum_q += cnt_eff * q
                neg += cnt_eff * q * q * diff
            else:
                for c in self._children[node]:
                    if c >= 0:
                        stack.append(c)
        return neg, sum_q


class QuadTree(SpTree):
    """2-D Barnes-Hut tree (parity: ``quadtree/QuadTree.java``)."""

    def __init__(self, points: np.ndarray):
        points = np.asarray(points, dtype=np.float64)
        if points.shape[1] != 2:
            raise ValueError("QuadTree requires 2-D points")
        super().__init__(points)
