// Barnes-Hut t-SNE gradient kernel: SpTree build + edge/non-edge forces.
//
// Role parity: reference clustering/sptree/SpTree.java (computeNonEdgeForces
// :computeEdgeForces) + plot/BarnesHutTsne.java's gradient — the reference
// runs these in Java (JIT-compiled); Python tree walks are ~100x too slow at
// real scale, so this framework puts the walk in C++ behind ctypes
// (clustering/native.py), with clustering/sptree.py as the pure-Python
// correctness oracle.
//
// Build: g++ -O3 -march=native -shared -fPIC -o _sptree.so _sptree.cpp

#include <cmath>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct SPTree {
    int d;
    int n_children;
    std::vector<double> center;    // [nodes, d] cell centers
    std::vector<double> width;     // [nodes, d] half-widths
    std::vector<double> com;       // [nodes, d] centers of mass
    std::vector<long> count;       // [nodes]
    std::vector<long> point;       // [nodes] leaf payload or -1
    std::vector<long> children;    // [nodes, n_children]
    std::vector<double> maxw;      // [nodes] max half-width (theta test)
    const double* pts;
    long n_nodes = 0;

    SPTree(const double* Y, long n, int dim) : d(dim), pts(Y) {
        n_children = 1 << d;
        long cap = 4 * n + 64;
        center.resize((size_t)cap * d);
        width.resize((size_t)cap * d);
        com.assign((size_t)cap * d, 0.0);
        count.assign(cap, 0);
        point.assign(cap, -1);
        children.assign((size_t)cap * n_children, -1);
        maxw.assign(cap, 0.0);

        std::vector<double> lo(d, 1e300), hi(d, -1e300);
        for (long i = 0; i < n; i++)
            for (int a = 0; a < d; a++) {
                double v = Y[i * d + a];
                if (v < lo[a]) lo[a] = v;
                if (v > hi[a]) hi[a] = v;
            }
        n_nodes = 1;
        double mw = 0.0;
        for (int a = 0; a < d; a++) {
            center[a] = (lo[a] + hi[a]) / 2.0;
            double h = (hi[a] - lo[a]) / 2.0;
            if (h < 1e-10) h = 1e-10;
            width[a] = h * 1.0000001;
            if (width[a] > mw) mw = width[a];
        }
        maxw[0] = mw;
        for (long i = 0; i < n; i++) insert(0, i);
    }

    void grow() {
        size_t cap = count.size(), ncap = cap * 2;
        center.resize(ncap * d);
        width.resize(ncap * d);
        com.resize(ncap * d, 0.0);
        count.resize(ncap, 0);
        point.resize(ncap, -1);
        children.resize(ncap * n_children, -1);
        maxw.resize(ncap, 0.0);
    }

    int child_index(long node, const double* p) const {
        int idx = 0;
        for (int a = 0; a < d; a++)
            if (p[a] > center[node * d + a]) idx |= (1 << a);
        return idx;
    }

    long alloc_child(long node, int ci) {
        if ((size_t)n_nodes >= count.size()) grow();
        long nn = n_nodes++;
        double mw = 0.0;
        for (int a = 0; a < d; a++) {
            double h = width[node * d + a] / 2.0;
            width[nn * d + a] = h;
            center[nn * d + a] = center[node * d + a]
                + (((ci >> a) & 1) ? h : -h);
            if (h > mw) mw = h;
        }
        maxw[nn] = mw;
        children[node * n_children + ci] = nn;
        return nn;
    }

    bool same_point(long i, long j) const {
        for (int a = 0; a < d; a++)
            if (std::fabs(pts[i * d + a] - pts[j * d + a]) > 1e-12)
                return false;
        return true;
    }

    void insert(long node, long i) {
        const double* p = pts + i * d;
        while (true) {
            long c = count[node];
            for (int a = 0; a < d; a++)
                com[node * d + a] =
                    (com[node * d + a] * c + p[a]) / (c + 1);
            count[node] = c + 1;
            if (c == 0) { point[node] = i; return; }
            if (point[node] >= 0) {
                long j = point[node];
                if (same_point(i, j)) return;  // duplicate: mass only
                point[node] = -1;
                int cj = child_index(node, pts + j * d);
                long ch = children[node * n_children + cj];
                if (ch < 0) ch = alloc_child(node, cj);
                insert(ch, j);
            }
            int ci = child_index(node, p);
            long ch = children[node * n_children + ci];
            if (ch < 0) ch = alloc_child(node, ci);
            node = ch;
        }
    }

    // repulsive force on point i; adds into neg[d], returns sum_Q part
    double non_edge_forces(long i, double theta2, double* neg,
                           std::vector<long>& stack) const {
        const double* p = pts + i * d;
        double sum_q = 0.0;
        stack.clear();
        stack.push_back(0);
        while (!stack.empty()) {
            long node = stack.back();
            stack.pop_back();
            long cnt = count[node];
            if (cnt == 0) continue;
            if (point[node] == i && cnt == 1) continue;
            double d2 = 0.0;
            for (int a = 0; a < d; a++) {
                double diff = p[a] - com[node * d + a];
                d2 += diff * diff;
            }
            bool leaf = point[node] >= 0;
            if (leaf || maxw[node] * maxw[node] < theta2 * d2) {
                long eff = cnt - (point[node] == i ? 1 : 0);
                if (eff <= 0) continue;
                double q = 1.0 / (1.0 + d2);
                sum_q += eff * q;
                double qq = eff * q * q;
                for (int a = 0; a < d; a++)
                    neg[a] += qq * (p[a] - com[node * d + a]);
            } else {
                const long* ch = &children[node * n_children];
                for (int k = 0; k < n_children; k++)
                    if (ch[k] >= 0) stack.push_back(ch[k]);
            }
        }
        return sum_q;
    }
};

}  // namespace

extern "C" {

// Full BH t-SNE gradient. Y [n,d] row-major, P in CSR (row_ptr [n+1],
// cols/vals [nnz]). Writes dC [n,d] and *kl (exact KL given the BH sum_Q
// approximation). Returns 0 on success.
int bh_tsne_gradient(const double* Y, long n, int d,
                     const long* row_ptr, const long* cols,
                     const double* vals, double theta,
                     double* dC, double* kl) {
    SPTree tree(Y, n, d);
    std::vector<double> neg((size_t)n * d, 0.0);
    std::vector<double> pos((size_t)n * d, 0.0);
    double sum_q = 0.0;
    std::vector<long> stack;
    stack.reserve(256);
    double theta2 = theta * theta;
    for (long i = 0; i < n; i++)
        sum_q += tree.non_edge_forces(i, theta2, &neg[i * d], stack);
    if (sum_q <= 0.0) sum_q = 1e-12;

    double kl_acc = 0.0;
    for (long i = 0; i < n; i++) {
        const double* pi = Y + i * d;
        for (long e = row_ptr[i]; e < row_ptr[i + 1]; e++) {
            long j = cols[e];
            double d2 = 0.0;
            for (int a = 0; a < d; a++) {
                double diff = pi[a] - Y[j * d + a];
                d2 += diff * diff;
            }
            double q = 1.0 / (1.0 + d2);
            double pq = vals[e] * q;
            for (int a = 0; a < d; a++)
                pos[i * d + a] += pq * (pi[a] - Y[j * d + a]);
            double qn = q / sum_q;
            if (vals[e] > 1e-12)
                kl_acc += vals[e] * std::log(vals[e] / (qn > 1e-12
                                                        ? qn : 1e-12));
        }
    }
    for (long i = 0; i < n; i++)
        for (int a = 0; a < d; a++)
            dC[i * d + a] = 4.0 * (pos[i * d + a]
                                   - neg[i * d + a] / sum_q);
    if (kl) *kl = kl_acc;
    return 0;
}

// Standalone non-edge forces for one point (test hook mirroring
// SpTree.computeNonEdgeForces).
double bh_non_edge_forces(const double* Y, long n, int d, long i,
                          double theta, double* neg) {
    SPTree tree(Y, n, d);
    std::vector<long> stack;
    for (int a = 0; a < d; a++) neg[a] = 0.0;
    return tree.non_edge_forces(i, theta * theta, neg, stack);
}

}  // extern "C"
