"""KMeans (parity: reference ``clustering/kmeans/KMeansClustering.java`` over
``algorithm/BaseClusteringAlgorithm.java`` — iterative assign/update with a
distance function and convergence condition).

TPU-native: k-means++ seeding on host; each iteration is ONE jitted program:
[n,k] squared-distance matrix via the ||a-b||² = ||a||²+||b||²-2ab expansion
(MXU matmul), argmin assignment, segment-sum centroid update.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np


def _sq_dists(x, c):
    import jax.numpy as jnp
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)
    return x2 + c2 - 2.0 * (x @ c.T)


@functools.partial(__import__("jax").jit, static_argnames=("k",))
def _kmeans_iter(x, centroids, *, k):
    import jax
    import jax.numpy as jnp
    d = _sq_dists(x, centroids)
    assign = jnp.argmin(d, axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=x.dtype)
    counts = jnp.sum(one_hot, axis=0)
    sums = one_hot.T @ x
    new_c = jnp.where(counts[:, None] > 0,
                      sums / jnp.maximum(counts[:, None], 1.0),
                      centroids)
    cost = jnp.sum(jnp.min(d, axis=1))
    return new_c, assign, cost


class KMeansClustering:
    """Usage (reference: ``KMeansClustering.setup(k, maxIter, distance)``)::

        km = KMeansClustering(k=3, max_iterations=100, seed=0)
        assignments = km.fit(points).predict(points)
    """

    def __init__(self, k: int, max_iterations: int = 100,
                 tolerance: float = 1e-6, seed: Optional[int] = None):
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.tolerance = float(tolerance)
        self.seed = seed
        self.centroids: Optional[np.ndarray] = None
        self.cost: Optional[float] = None
        self.iterations_run = 0

    def _kmeanspp_init(self, x: np.ndarray, rng) -> np.ndarray:
        n = x.shape[0]
        centroids = [x[rng.integers(0, n)]]
        for _ in range(1, self.k):
            d2 = np.min(
                ((x[:, None, :] - np.stack(centroids)[None]) ** 2).sum(-1),
                axis=1)
            probs = d2 / max(d2.sum(), 1e-12)
            centroids.append(x[rng.choice(n, p=probs)])
        return np.stack(centroids)

    def fit(self, points) -> "KMeansClustering":
        import jax.numpy as jnp

        x = np.asarray(points, dtype=np.float32)
        if x.shape[0] < self.k:
            raise ValueError(f"need at least k={self.k} points, got {x.shape[0]}")
        rng = np.random.default_rng(self.seed)
        c = jnp.asarray(self._kmeanspp_init(x, rng))
        xj = jnp.asarray(x)
        prev_cost = None
        for it in range(self.max_iterations):
            c, _, cost = _kmeans_iter(xj, c, k=self.k)
            cost = float(cost)
            self.iterations_run = it + 1
            if prev_cost is not None and abs(prev_cost - cost) <= \
                    self.tolerance * max(abs(prev_cost), 1.0):
                prev_cost = cost
                break
            prev_cost = cost
        self.centroids = np.asarray(c)
        self.cost = prev_cost
        return self

    def predict(self, points) -> np.ndarray:
        import jax.numpy as jnp
        if self.centroids is None:
            raise ValueError("fit() first")
        d = _sq_dists(jnp.asarray(np.asarray(points, np.float32)),
                      jnp.asarray(self.centroids))
        return np.asarray(jnp.argmin(d, axis=1))
