"""Vantage-point tree (parity: reference ``vptree/VPTree.java`` — metric-space
nearest-neighbour search; used by the reference for wordsNearest-style
queries)."""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_VPNode"] = None
        self.outside: Optional["_VPNode"] = None


class VPTree:
    """distance: "euclidean" (default) or "cosine" (parity: VPTree's
    configurable distance function)."""

    def __init__(self, points, distance: str = "euclidean",
                 seed: Optional[int] = 0):
        self.points = np.asarray(points, dtype=np.float64)
        self.distance = distance
        if distance == "cosine":
            norms = np.linalg.norm(self.points, axis=1, keepdims=True)
            self._normed = self.points / np.maximum(norms, 1e-12)
        self._rng = np.random.default_rng(seed)
        self.root = self._build(list(range(len(self.points))))

    def _dist(self, i: int, q: np.ndarray) -> float:
        if self.distance == "cosine":
            qn = q / max(np.linalg.norm(q), 1e-12)
            return float(1.0 - self._normed[i] @ qn)
        return float(np.linalg.norm(self.points[i] - q))

    def _build(self, idx: List[int]) -> Optional[_VPNode]:
        if not idx:
            return None
        vp = idx[self._rng.integers(0, len(idx))]
        rest = [i for i in idx if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        dists = [self._dist(i, self.points[vp]) for i in rest]
        node.threshold = float(np.median(dists))
        inside = [i for i, d in zip(rest, dists) if d <= node.threshold]
        outside = [i for i, d in zip(rest, dists) if d > node.threshold]
        if not outside and len(inside) == len(rest):
            # all distances equal (duplicate points): split arbitrarily so
            # the recursion always makes progress
            half = len(inside) // 2 or 1
            inside, outside = inside[:half], inside[half:]
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def knn(self, query, k: int) -> List[Tuple[int, float]]:
        import heapq
        q = np.asarray(query, dtype=np.float64)
        heap: List[Tuple[float, int]] = []

        def search(node: Optional[_VPNode]):
            if node is None:
                return
            d = self._dist(node.index, q)
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            tau = -heap[0][0] if len(heap) == k else np.inf
            if d <= node.threshold:
                search(node.inside)
                if d + tau > node.threshold:
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau <= node.threshold:
                    search(node.inside)

        search(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])

    def nn(self, query) -> Tuple[int, float]:
        return self.knn(query, 1)[0]
