"""KD-tree (parity: reference ``kdtree/KDTree.java`` — axis-cycling median
tree with nearest-neighbour and k-NN search)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index: int, axis: int):
        self.index = index
        self.axis = axis
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    def __init__(self, points):
        self.points = np.asarray(points, dtype=np.float64)
        if self.points.ndim != 2:
            raise ValueError("points must be [n, d]")
        self.dims = self.points.shape[1]
        idx = np.arange(len(self.points))
        self.root = self._build(idx, depth=0)

    def _build(self, idx: np.ndarray, depth: int) -> Optional[_Node]:
        if len(idx) == 0:
            return None
        axis = depth % self.dims
        order = np.argsort(self.points[idx, axis], kind="stable")
        idx = idx[order]
        mid = len(idx) // 2
        node = _Node(int(idx[mid]), axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def size(self) -> int:
        return len(self.points)

    def nn(self, query) -> Tuple[int, float]:
        """Nearest neighbour: (index, distance)."""
        res = self.knn(query, 1)
        return res[0]

    def knn(self, query, k: int) -> List[Tuple[int, float]]:
        """k nearest: [(index, distance)] sorted ascending."""
        import heapq
        q = np.asarray(query, dtype=np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated dist

        def search(node: Optional[_Node]):
            if node is None:
                return
            p = self.points[node.index]
            d = float(np.linalg.norm(p - q))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = q[node.axis] - p[node.axis]
            near, far = (node.left, node.right) if diff <= 0 else \
                (node.right, node.left)
            search(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                search(far)

        search(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])
