"""Clustering + spatial search structures.

Parity: reference ``deeplearning4j-core/.../clustering/`` — ``kmeans/``
(KMeansClustering over the generic ``BaseClusteringAlgorithm``),
``kdtree/KDTree.java``, ``vptree/VPTree.java`` (nearest-neighbour search),
``sptree/``/``quadtree/`` (used by Barnes-Hut t-SNE, see ``plot/``).

TPU-native: KMeans assignment/update are jitted all-pairs distance programs
(one XLA program per iteration — the MXU eats the [n, k] distance matmul);
the tree structures are host-side numpy (pointer-chasing search does not
belong on a systolic array).
"""

from .kdtree import KDTree
from .kmeans import KMeansClustering
from .vptree import VPTree

__all__ = ["KMeansClustering", "KDTree", "VPTree"]
