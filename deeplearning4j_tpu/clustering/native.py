"""ctypes loader for the C++ Barnes-Hut kernel (_sptree.cpp).

Compiles on first use with g++ (cached next to the source, keyed by source
hash) and binds via ctypes — the framework's native-runtime pattern for
host-side hot loops the reference ran in JIT-compiled Java. Falls back to
None when no compiler is available; callers then use the pure-Python
:mod:`.sptree` implementation.
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional

import numpy as np

from ..util.native import compile_and_load

_lib: Optional[ctypes.CDLL] = None
_tried = False


def load() -> Optional[ctypes.CDLL]:
    """The compiled kernel, or None (then use sptree.SpTree)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    lib = compile_and_load(Path(__file__).parent / "_sptree.cpp")
    if lib is None:
        return None
    lib.bh_tsne_gradient.restype = ctypes.c_int
    lib.bh_tsne_gradient.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_double), ctypes.c_double,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
    lib.bh_non_edge_forces.restype = ctypes.c_double
    lib.bh_non_edge_forces.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_long, ctypes.c_int,
        ctypes.c_long, ctypes.c_double, ctypes.POINTER(ctypes.c_double)]
    _lib = lib
    return _lib


def _dptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _lptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_long))


def bh_gradient(y: np.ndarray, row_ptr: np.ndarray, cols: np.ndarray,
                vals: np.ndarray, theta: float):
    """BH t-SNE gradient via the native kernel. Returns (dC [n,d], kl).
    Raises if the kernel is unavailable — callers check load() first."""
    lib = load()
    if lib is None:
        raise RuntimeError("native SpTree kernel unavailable")
    y = np.ascontiguousarray(y, dtype=np.float64)
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    n, d = y.shape
    dc = np.zeros_like(y)
    kl = ctypes.c_double(0.0)
    rc = lib.bh_tsne_gradient(_dptr(y), n, d, _lptr(row_ptr), _lptr(cols),
                              _dptr(vals), float(theta), _dptr(dc),
                              ctypes.byref(kl))
    if rc != 0:
        raise RuntimeError(f"bh_tsne_gradient failed rc={rc}")
    return dc, float(kl.value)


def non_edge_forces(y: np.ndarray, i: int, theta: float):
    """Single-point repulsion via native SpTree (test hook). Returns
    (neg_force [d], sum_q)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native SpTree kernel unavailable")
    y = np.ascontiguousarray(y, dtype=np.float64)
    n, d = y.shape
    neg = np.zeros(d)
    sq = lib.bh_non_edge_forces(_dptr(y), n, d, int(i), float(theta),
                                _dptr(neg))
    return neg, float(sq)
