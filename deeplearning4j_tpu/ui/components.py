"""UI components DSL: server-side chart/table/text components that
serialize to JSON and render to standalone HTML.

Parity: reference ``deeplearning4j-ui-components`` —
``components/chart/ChartLine.java``, ``ChartHistogram.java``,
``ChartTimeline.java``, ``ChartScatter.java``, ``table/ComponentTable.java``,
``text/ComponentText.java``, ``component/ComponentDiv.java`` and
``standalone/StaticPageUtil.java`` (render a component list into one
self-contained HTML page). The reference serialized components to JSON for
a JS renderer; here rendering is server-side inline SVG so the output needs
no script assets — same contract (build components anywhere, ship one file),
TPU-era dependency count (zero).

Used by :meth:`..parallel.stats.TrainingStats.export_html` the way Spark
training stats used ui-components for ``StatsUtils.exportStatsAsHtml``.
"""

from __future__ import annotations

import html as _html
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, type] = {}

_PALETTE = ["#4c78a8", "#f58518", "#54a24b", "#e45756", "#72b7b2",
            "#b279a2", "#eeca3b", "#9d755d"]


def _register(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


class Component:
    """Base: every component serializes to ``{"type": ..., ...fields}`` and
    renders itself to an SVG/HTML fragment."""

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items()}
        d["type"] = type(self).__name__
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Component":
        d = dict(d)
        t = d.pop("type")
        try:
            cls = _REGISTRY[t]
        except KeyError:
            raise ValueError(f"unknown component type {t!r}") from None
        obj = cls.__new__(cls)
        obj.__dict__.update(d)
        return obj

    @staticmethod
    def from_json(s: str) -> "Component":
        return Component.from_dict(json.loads(s))

    def render(self) -> str:  # HTML fragment
        raise NotImplementedError


def _axes(width, height, pad, xmin, xmax, ymin, ymax) -> Tuple[str, Any, Any]:
    """Axis frame + tick labels; returns (svg fragment, sx, sy mappers)."""
    xspan = (xmax - xmin) or 1.0
    yspan = (ymax - ymin) or 1.0
    sx = lambda x: pad + (x - xmin) / xspan * (width - 2 * pad)
    sy = lambda y: height - pad - (y - ymin) / yspan * (height - 2 * pad)
    frag = (f'<rect x="{pad}" y="{pad}" width="{width - 2 * pad}" '
            f'height="{height - 2 * pad}" fill="none" stroke="#bbb"/>'
            f'<text x="{pad}" y="{height - 4}" font-size="10">{xmin:.4g}</text>'
            f'<text x="{width - pad - 30}" y="{height - 4}" font-size="10">'
            f'{xmax:.4g}</text>'
            f'<text x="2" y="{height - pad}" font-size="10">{ymin:.4g}</text>'
            f'<text x="2" y="{pad + 10}" font-size="10">{ymax:.4g}</text>')
    return frag, sx, sy


@_register
class ComponentText(Component):
    """Plain text block (ref ``text/ComponentText.java``)."""

    def __init__(self, text: str, *, size: int = 13):
        self.text = text
        self.size = int(size)

    def render(self) -> str:
        return (f'<p style="font-size:{self.size}px">'
                f'{_html.escape(self.text)}</p>')


@_register
class ComponentTable(Component):
    """Header + rows table (ref ``table/ComponentTable.java``)."""

    def __init__(self, header: Sequence[str], rows: Sequence[Sequence[Any]],
                 *, title: str = ""):
        self.title = title
        self.header = list(header)
        self.rows = [[str(c) for c in r] for r in rows]

    def render(self) -> str:
        head = "".join(f"<th>{_html.escape(h)}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(c)}</td>" for c in r)
            + "</tr>" for r in self.rows)
        t = (f"<h3>{_html.escape(self.title)}</h3>" if self.title else "")
        return (f'{t}<table class="dl4j-table"><tr>{head}</tr>{body}</table>')


@_register
class ChartLine(Component):
    """Multi-series line chart (ref ``chart/ChartLine.java``)."""

    def __init__(self, title: str = "", *, width: int = 700,
                 height: int = 260):
        self.title = title
        self.width = int(width)
        self.height = int(height)
        self.series: List[Dict[str, Any]] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        if len(x) != len(y):
            raise ValueError(f"series {name!r}: len(x) {len(x)} != "
                             f"len(y) {len(y)}")
        self.series.append({"name": name,
                            "x": [float(v) for v in x],
                            "y": [float(v) for v in y]})
        return self

    def render(self) -> str:
        w, h, pad = self.width, self.height, 36
        xs = [v for s in self.series for v in s["x"]]
        ys = [v for s in self.series for v in s["y"]]
        if not xs:
            return f"<h3>{_html.escape(self.title)}</h3><svg/>"
        frame, sx, sy = _axes(w, h, pad, min(xs), max(xs), min(ys), max(ys))
        paths, legend = [], []
        for i, s in enumerate(self.series):
            c = _PALETTE[i % len(_PALETTE)]
            d = "M" + " L".join(f"{sx(x):.1f},{sy(y):.1f}"
                                for x, y in zip(s["x"], s["y"]))
            paths.append(f'<path d="{d}" fill="none" stroke="{c}" '
                         f'stroke-width="1.5"/>')
            legend.append(f'<tspan fill="{c}">■ '
                          f'{_html.escape(s["name"])}</tspan> ')
        leg = (f'<text x="{pad}" y="14" font-size="11">'
               + "".join(legend) + "</text>")
        return (f"<h3>{_html.escape(self.title)}</h3>"
                f'<svg width="{w}" height="{h}">{frame}{leg}'
                f'{"".join(paths)}</svg>')


@_register
class ChartScatter(Component):
    """Scatter chart (ref ``chart/ChartScatter.java``)."""

    def __init__(self, title: str = "", *, width: int = 700,
                 height: int = 420, point_size: float = 2.5):
        self.title = title
        self.width = int(width)
        self.height = int(height)
        self.point_size = float(point_size)
        self.series: List[Dict[str, Any]] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartScatter":
        if len(x) != len(y):
            raise ValueError(f"series {name!r}: len(x) != len(y)")
        self.series.append({"name": name,
                            "x": [float(v) for v in x],
                            "y": [float(v) for v in y]})
        return self

    def render(self) -> str:
        w, h, pad = self.width, self.height, 36
        xs = [v for s in self.series for v in s["x"]]
        ys = [v for s in self.series for v in s["y"]]
        if not xs:
            return f"<h3>{_html.escape(self.title)}</h3><svg/>"
        frame, sx, sy = _axes(w, h, pad, min(xs), max(xs), min(ys), max(ys))
        dots, legend = [], []
        for i, s in enumerate(self.series):
            c = _PALETTE[i % len(_PALETTE)]
            dots.extend(
                f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" '
                f'r="{self.point_size}" fill="{c}" fill-opacity="0.65"/>'
                for x, y in zip(s["x"], s["y"]))
            legend.append(f'<tspan fill="{c}">● '
                          f'{_html.escape(s["name"])}</tspan> ')
        leg = (f'<text x="{pad}" y="14" font-size="11">'
               + "".join(legend) + "</text>")
        return (f"<h3>{_html.escape(self.title)}</h3>"
                f'<svg width="{w}" height="{h}">{frame}{leg}'
                f'{"".join(dots)}</svg>')


@_register
class ChartHistogram(Component):
    """Histogram from bin edges + counts (ref ``chart/ChartHistogram.java``)."""

    def __init__(self, title: str = "", *, width: int = 700,
                 height: int = 220):
        self.title = title
        self.width = int(width)
        self.height = int(height)
        self.lower: List[float] = []
        self.upper: List[float] = []
        self.counts: List[float] = []

    def add_bin(self, lower: float, upper: float,
                count: float) -> "ChartHistogram":
        self.lower.append(float(lower))
        self.upper.append(float(upper))
        self.counts.append(float(count))
        return self

    def render(self) -> str:
        w, h, pad = self.width, self.height, 36
        if not self.counts:
            return f"<h3>{_html.escape(self.title)}</h3><svg/>"
        frame, sx, sy = _axes(w, h, pad, min(self.lower), max(self.upper),
                              0.0, max(self.counts) or 1.0)
        bars = []
        for lo, up, c in zip(self.lower, self.upper, self.counts):
            x0, x1 = sx(lo), sx(up)
            y = sy(c)
            bars.append(f'<rect x="{x0:.1f}" y="{y:.1f}" '
                        f'width="{max(x1 - x0 - 0.5, 0.5):.1f}" '
                        f'height="{h - pad - y:.1f}" fill="#4c78a8"/>')
        return (f"<h3>{_html.escape(self.title)}</h3>"
                f'<svg width="{w}" height="{h}">{frame}{"".join(bars)}</svg>')


@_register
class ChartTimeline(Component):
    """Swimlane timeline (ref ``chart/ChartTimeline.java``): named lanes,
    each holding [start, end, label] entries."""

    def __init__(self, title: str = "", *, width: int = 960,
                 lane_height: int = 28):
        self.title = title
        self.width = int(width)
        self.lane_height = int(lane_height)
        self.lanes: List[Dict[str, Any]] = []

    def add_lane(self, name: str,
                 entries: Sequence[Tuple[float, float, str]]
                 ) -> "ChartTimeline":
        self.lanes.append({
            "name": name,
            "entries": [[float(s), float(e), str(lbl)]
                        for s, e, lbl in entries]})
        return self

    def render(self) -> str:
        w, lane_h, label_w = self.width, self.lane_height, 160.0
        ends = [e for lane in self.lanes for _, e, _ in lane["entries"]]
        end = max(ends) if ends else 1.0
        scale = (w - label_w - 20) / max(end, 1e-9)
        rows = []
        for i, lane in enumerate(self.lanes):
            y = 30 + i * lane_h
            color = _PALETTE[i % len(_PALETTE)]
            rows.append(f'<text x="4" y="{y + 18}" font-size="12">'
                        f'{_html.escape(lane["name"])}</text>')
            for s, e, lbl in lane["entries"]:
                x = label_w + s * scale
                bw = max((e - s) * scale, 0.75)
                rows.append(
                    f'<rect x="{x:.2f}" y="{y + 4}" width="{bw:.2f}" '
                    f'height="{lane_h - 8}" fill="{color}">'
                    f'<title>{_html.escape(lbl)}</title></rect>')
        h = 40 + len(self.lanes) * lane_h
        return (f"<h3>{_html.escape(self.title)}</h3>"
                f'<svg width="{w}" height="{h}">{"".join(rows)}</svg>')


@_register
class ComponentDiv(Component):
    """Container of child components (ref ``component/ComponentDiv.java``)."""

    def __init__(self, *children: Component, style: str = ""):
        self.style = style
        self.children = [c.to_dict() for c in children]

    def render(self) -> str:
        inner = "".join(Component.from_dict(c).render()
                        for c in self.children)
        s = f' style="{_html.escape(self.style, quote=True)}"' \
            if self.style else ""
        return f"<div{s}>{inner}</div>"


class StaticPageUtil:
    """Render components into one standalone HTML page
    (ref ``standalone/StaticPageUtil.java``)."""

    _CSS = ("body{font-family:sans-serif;margin:20px;background:#fafafa}"
            ".dl4j-card{background:#fff;border:1px solid #ddd;"
            "border-radius:6px;padding:12px 16px;margin-bottom:14px;"
            "max-width:1000px}"
            "table.dl4j-table{border-collapse:collapse}"
            ".dl4j-table td,.dl4j-table th{border:1px solid #ccc;"
            "padding:4px 8px;font-size:13px}"
            "h2{font-size:1.25em}h3{font-size:1.0em;margin:4px 0}")

    @staticmethod
    def render_html(components: Sequence[Component],
                    title: str = "deeplearning4j_tpu report") -> str:
        cards = "".join(f'<div class="dl4j-card">{c.render()}</div>'
                        for c in components)
        return (f'<!DOCTYPE html><html><head><meta charset="utf-8">'
                f"<title>{_html.escape(title)}</title>"
                f"<style>{StaticPageUtil._CSS}</style></head><body>"
                f"<h2>{_html.escape(title)}</h2>{cards}</body></html>")

    @staticmethod
    def save_html(components: Sequence[Component], path: str,
                  title: str = "deeplearning4j_tpu report") -> None:
        with open(path, "w") as f:
            f.write(StaticPageUtil.render_html(components, title))
