"""StatsListener: the training-stats producer.

Parity: reference ``ui/stats/StatsListener.java`` — ``iterationDone``
(``:222``) collecting score, iteration timing, memory (``:257-298``), and
param/gradient/update norms + histograms, posted as Persistable records to a
StatsStorageRouter. Here device memory comes from JAX's
``memory_stats()`` when the backend exposes it; histograms are numpy.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from ..optimize.listeners import TrainingListener
from ..storage.stats_storage import Persistable, StatsStorageRouter

TYPE_ID = "StatsListener"

logger = logging.getLogger("deeplearning4j_tpu")


def _host_memory_bytes() -> Optional[int]:
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def _device_memory_stats() -> Optional[Dict[str, Dict[str, int]]]:
    """Per-device memory stats keyed by device label (the UI pane's
    feed). None when no backend exposes memory_stats (CPU)."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax
        for d in jax.devices():
            stats = d.memory_stats()
            if stats:
                out[f"{d.platform}:{d.id}"] = {
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(
                        stats.get("peak_bytes_in_use", 0))}
    except Exception:
        pass
    return out or None


# the gauges themselves live in util/profiling (nothing UI-specific about
# HBM pressure — the serving layer registers them too); re-exported here
# because this module's listener is the training-side registration point
from ..util.profiling import _MEMORY_KINDS  # noqa: F401  (test fixture)
from ..util.profiling import register_device_memory_gauges  # noqa: F401


def _histogram(arr: np.ndarray, bins: int = 20) -> Dict[str, Any]:
    counts, edges = np.histogram(arr, bins=bins)
    return {"counts": counts.tolist(),
            "min": float(edges[0]), "max": float(edges[-1])}


class StatsListener(TrainingListener):
    """Collects stats every ``frequency`` iterations and routes them to
    storage.

    Two model-internals paths:

    - **On-device** (``device_stats``): the model's stats-enabled train
      step (``net.enable_health_stats()`` / ``util.health``) computes
      per-layer norms, update:param ratios, activation stats and
      log-bucket histograms INSIDE the train dispatch; this listener
      reads the small stats pytree with ONE device→host sync per
      collected window — the score rides in the same pytree, so the
      LazyScore is never separately synced. ``device_stats=True``
      enables the pass on the model; ``None`` (default) consumes it when
      already enabled; ``False`` never uses it (the host path below is
      the parity oracle).
    - **Legacy host** (``collect_histograms`` / ``collect_norms``):
      device_get every param tensor each ``histogram_frequency``-th
      collected window and reduce in numpy. Histograms are only
      materialized when ``collect_histograms=True`` — norms-only
      collection (``collect_norms=True``) still pays the transfer but
      not the binning.

    Async-dispatch contract: ``score`` arrives as a lazy on-device value
    (``util.ingest.LazyScore``); this listener reads it only on collected
    iterations, so at ``frequency=N`` the fit loop pays exactly one
    device→host sync per N steps — off-frequency iterations return
    before any sync and never block the dispatch pipeline."""

    def __init__(self, router: StatsStorageRouter, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "worker_0",
                 collect_histograms: bool = False,
                 histogram_frequency: int = 10,
                 collect_norms: bool = False,
                 device_stats: Optional[bool] = None):
        self.router = router
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session_{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.collect_norms = collect_norms
        self.histogram_frequency = max(1, int(histogram_frequency))
        self.device_stats = device_stats
        self._device_requested = False
        self._device_misses = 0      # collected windows with no snapshot
        # HBM pressure belongs on /metrics, not just in posted records
        register_device_memory_gauges()
        # time/iteration of the last COLLECTED iteration: per-iteration
        # duration is (now - then) / iterations-elapsed. (Touching this
        # every iteration_done under-reported iteration_ms by ~frequency×.)
        self._last_time: Optional[float] = None
        self._last_iteration: Optional[int] = None
        self._static_posted = False
        self._prev_params: Optional[Dict[str, np.ndarray]] = None

    # -- listener hooks --
    def _maybe_enable_device_stats(self, model) -> None:
        if self.device_stats and not self._device_requested:
            if hasattr(model, "enable_health_stats"):
                model.enable_health_stats()
            self._device_requested = True

    def on_epoch_start(self, model, epoch: int) -> None:
        self._maybe_enable_device_stats(model)
        if not self._static_posted:
            self._post_static(model)

    def iteration_done(self, model, iteration: int, score) -> None:
        self._maybe_enable_device_stats(model)
        if not self._static_posted:
            self._post_static(model)
        if iteration % self.frequency:
            return
        now = time.perf_counter()
        if self._last_time is None:
            duration_ms = None
        else:
            elapsed_iters = max(1, iteration - self._last_iteration)
            duration_ms = 1000.0 * (now - self._last_time) / elapsed_iters
        self._last_time = now
        self._last_iteration = iteration
        snap = None
        ds = None
        if self.device_stats is not False:
            from ..util import health as _health
            ds = _health.latest_stats(model)
            # only trust a snapshot produced by THIS iteration's dispatch
            # (fit_scan replays fire listeners for window-interior
            # iterations whose snapshot is the window's last step)
            if ds is not None and ds.iteration == iteration:
                snap = ds.value()   # the window's ONE device→host sync
        if snap is not None:
            from ..util import health as _health
            loss = (snap.get(_health.MODEL_KEY) or {}).get("loss")
            score_val = float(score) if loss is None else float(loss)
        else:
            score_val = float(score)
        data: Dict[str, Any] = {
            "iteration": int(iteration),
            "score": score_val,
            "iteration_ms": duration_ms,
        }
        mem = _host_memory_bytes()
        if mem is not None:
            data["host_memory_bytes"] = mem
        dev = _device_memory_stats()
        if dev is not None:
            data["device_memory"] = dev
        if snap is not None:
            self._device_misses = 0
            data["model_stats"] = {"iteration": int(iteration),
                                   "layers": snap}
            data["parameters"] = self._device_param_view(snap)
        else:
            # device_stats=True but NO DeviceStats object exists at all
            # (a mismatched-iteration snapshot is a cadence artifact of
            # fit_scan interior iterations, not absence): the first miss
            # is expected (the stats variant only traces on the NEXT fit
            # after enabling); repeated misses mean this net's step never
            # produces them (e.g. a sharded train_step override) — warn
            # once and fall back to the legacy host path so the listener
            # does not silently post nothing
            fallback = False
            if self.device_stats and ds is None:
                self._device_misses += 1
                fallback = self._device_misses >= 2
                if self._device_misses == 2:
                    logger.warning(
                        "StatsListener(device_stats=True): no on-device "
                        "stats snapshot after %d collected windows — this "
                        "net's train step does not produce them (sharded "
                        "override?); falling back to the host parameter "
                        "path", self._device_misses)
            if ((self.collect_histograms or self.collect_norms or fallback)
                    and (iteration // self.frequency)
                    % self.histogram_frequency == 0):
                data["parameters"] = self._param_stats(
                    model, histograms=self.collect_histograms)
        self.router.put_update(Persistable(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(), data=data))

    # -- internals --
    def _post_static(self, model) -> None:
        info: Dict[str, Any] = {
            "model_class": type(model).__name__,
            "start_time": time.time(),
            "pid": os.getpid(),
        }
        try:
            info["num_params"] = int(model.num_params())
            info["config_json"] = model.conf.to_json()
        except Exception:
            pass
        try:
            import jax
            info["devices"] = [str(d) for d in jax.devices()]
        except Exception:
            pass
        self.router.put_static_info(Persistable(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(), data=info))
        self._static_posted = True

    def _device_param_view(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """Project an on-device stats snapshot into the ``parameters``
        record shape the UI's histogram/norm panes consume — PER LAYER
        (the device pass reduces per layer, not per tensor), histograms
        over fixed log10(|x|) edges (``log10_abs`` marks the axis)."""
        from ..util import health as _health
        out: Dict[str, Any] = {}
        lo, hi = _health.HIST_LOG_LO, _health.HIST_LOG_HI
        for name, e in _health.layer_items(snap):
            if "param_norm" not in e:
                continue
            entry: Dict[str, Any] = {
                "norm": e["param_norm"],
                "update": {"norm": e.get("update_norm")},
                "update_ratio": e.get("update_ratio"),
            }
            if "param_hist" in e:
                entry["histogram"] = {"counts": e["param_hist"],
                                      "min": lo, "max": hi,
                                      "log10_abs": True}
                entry["update"]["histogram"] = {
                    "counts": e.get("update_hist"),
                    "min": lo, "max": hi, "log10_abs": True}
            for k in ("act_mean", "act_std", "act_zero_frac"):
                if k in e:
                    entry[k] = e[k]
            out[name] = entry
        return out

    def _param_stats(self, model, histograms: bool = True) -> Dict[str, Any]:
        """Per-parameter norms (and, when ``histograms``, numpy
        histograms), plus the same for the last inter-snapshot UPDATE
        (param delta — the reference's 'updates' view; with a
        jitted+donated train step the raw gradient is fused away, so the
        applied update is the observable quantity). This is the legacy
        HOST path: it transfers every param tensor — kept as the parity
        oracle for the on-device pass; histogram binning is skipped
        unless requested."""
        import jax
        out = {}
        prev = self._prev_params or {}
        snap: Dict[str, np.ndarray] = {}
        flat = jax.tree_util.tree_flatten_with_path(model.params)[0]
        for path, leaf in flat:
            name = jax.tree_util.keystr(path)
            arr = np.asarray(leaf).ravel()
            snap[name] = arr
            entry = {
                "norm": float(np.linalg.norm(arr)),
                "mean": float(arr.mean()),
                "std": float(arr.std()),
            }
            if histograms:
                entry["histogram"] = _histogram(arr)
            if name in prev and prev[name].shape == arr.shape:
                upd = arr - prev[name]
                entry["update"] = {
                    "norm": float(np.linalg.norm(upd)),
                    "mean": float(upd.mean()),
                    "std": float(upd.std()),
                }
                if histograms:
                    entry["update"]["histogram"] = _histogram(upd)
                # ratio of update magnitude to param magnitude — the
                # at-a-glance learning-rate health indicator
                pn = float(np.linalg.norm(arr))
                entry["update_ratio"] = (float(np.linalg.norm(upd) / pn)
                                         if pn > 0 else 0.0)
            out[name] = entry
        self._prev_params = snap
        return out
