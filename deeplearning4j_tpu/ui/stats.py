"""StatsListener: the training-stats producer.

Parity: reference ``ui/stats/StatsListener.java`` — ``iterationDone``
(``:222``) collecting score, iteration timing, memory (``:257-298``), and
param/gradient/update norms + histograms, posted as Persistable records to a
StatsStorageRouter. Here device memory comes from JAX's
``memory_stats()`` when the backend exposes it; histograms are numpy.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from ..optimize.listeners import TrainingListener
from ..storage.stats_storage import Persistable, StatsStorageRouter

TYPE_ID = "StatsListener"


def _host_memory_bytes() -> Optional[int]:
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def _device_memory_stats() -> Optional[Dict[str, Dict[str, int]]]:
    """Per-device memory stats keyed by device label (the UI pane's
    feed). None when no backend exposes memory_stats (CPU)."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax
        for d in jax.devices():
            stats = d.memory_stats()
            if stats:
                out[f"{d.platform}:{d.id}"] = {
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(
                        stats.get("peak_bytes_in_use", 0))}
    except Exception:
        pass
    return out or None


# the gauges themselves live in util/profiling (nothing UI-specific about
# HBM pressure — the serving layer registers them too); re-exported here
# because this module's listener is the training-side registration point
from ..util.profiling import _MEMORY_KINDS  # noqa: F401  (test fixture)
from ..util.profiling import register_device_memory_gauges  # noqa: F401


def _histogram(arr: np.ndarray, bins: int = 20) -> Dict[str, Any]:
    counts, edges = np.histogram(arr, bins=bins)
    return {"counts": counts.tolist(),
            "min": float(edges[0]), "max": float(edges[-1])}


class StatsListener(TrainingListener):
    """Collects stats every ``frequency`` iterations and routes them to
    storage. ``collect_histograms`` adds per-param histograms + norms
    (off by default: it syncs params to host).

    Async-dispatch contract: ``score`` arrives as a lazy on-device value
    (``util.ingest.LazyScore``); this listener reads it only on collected
    iterations, so at ``frequency=N`` the fit loop pays exactly one
    device→host sync per N steps — off-frequency iterations return
    before ``float(score)`` and never block the dispatch pipeline."""

    def __init__(self, router: StatsStorageRouter, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "worker_0",
                 collect_histograms: bool = False,
                 histogram_frequency: int = 10):
        self.router = router
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session_{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.histogram_frequency = max(1, int(histogram_frequency))
        # HBM pressure belongs on /metrics, not just in posted records
        register_device_memory_gauges()
        # time/iteration of the last COLLECTED iteration: per-iteration
        # duration is (now - then) / iterations-elapsed. (Touching this
        # every iteration_done under-reported iteration_ms by ~frequency×.)
        self._last_time: Optional[float] = None
        self._last_iteration: Optional[int] = None
        self._static_posted = False
        self._prev_params: Optional[Dict[str, np.ndarray]] = None

    # -- listener hooks --
    def on_epoch_start(self, model, epoch: int) -> None:
        if not self._static_posted:
            self._post_static(model)

    def iteration_done(self, model, iteration: int, score) -> None:
        if not self._static_posted:
            self._post_static(model)
        if iteration % self.frequency:
            return
        now = time.perf_counter()
        if self._last_time is None:
            duration_ms = None
        else:
            elapsed_iters = max(1, iteration - self._last_iteration)
            duration_ms = 1000.0 * (now - self._last_time) / elapsed_iters
        self._last_time = now
        self._last_iteration = iteration
        data: Dict[str, Any] = {
            "iteration": int(iteration),
            "score": float(score),
            "iteration_ms": duration_ms,
        }
        mem = _host_memory_bytes()
        if mem is not None:
            data["host_memory_bytes"] = mem
        dev = _device_memory_stats()
        if dev is not None:
            data["device_memory"] = dev
        if (self.collect_histograms
                and (iteration // self.frequency) % self.histogram_frequency == 0):
            data["parameters"] = self._param_stats(model)
        self.router.put_update(Persistable(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(), data=data))

    # -- internals --
    def _post_static(self, model) -> None:
        info: Dict[str, Any] = {
            "model_class": type(model).__name__,
            "start_time": time.time(),
            "pid": os.getpid(),
        }
        try:
            info["num_params"] = int(model.num_params())
            info["config_json"] = model.conf.to_json()
        except Exception:
            pass
        try:
            import jax
            info["devices"] = [str(d) for d in jax.devices()]
        except Exception:
            pass
        self.router.put_static_info(Persistable(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(), data=info))
        self._static_posted = True

    def _param_stats(self, model) -> Dict[str, Any]:
        """Per-parameter norms/histograms, plus the same for the last
        inter-snapshot UPDATE (param delta — the reference's 'updates' view;
        with a jitted+donated train step the raw gradient is fused away, so
        the applied update is the observable quantity)."""
        import jax
        out = {}
        prev = self._prev_params or {}
        snap: Dict[str, np.ndarray] = {}
        flat = jax.tree_util.tree_flatten_with_path(model.params)[0]
        for path, leaf in flat:
            name = jax.tree_util.keystr(path)
            arr = np.asarray(leaf).ravel()
            snap[name] = arr
            entry = {
                "norm": float(np.linalg.norm(arr)),
                "mean": float(arr.mean()),
                "std": float(arr.std()),
                "histogram": _histogram(arr),
            }
            if name in prev and prev[name].shape == arr.shape:
                upd = arr - prev[name]
                entry["update"] = {
                    "norm": float(np.linalg.norm(upd)),
                    "mean": float(upd.mean()),
                    "std": float(upd.std()),
                    "histogram": _histogram(upd),
                }
                # ratio of update magnitude to param magnitude — the
                # at-a-glance learning-rate health indicator
                pn = float(np.linalg.norm(arr))
                entry["update_ratio"] = (float(np.linalg.norm(upd) / pn)
                                         if pn > 0 else 0.0)
            out[name] = entry
        self._prev_params = snap
        return out
