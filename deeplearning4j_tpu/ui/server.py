"""UIServer: the training dashboard.

Parity: reference ``deeplearning4j-play/.../PlayUIServer.java`` +
``api/UIServer.java`` (``getInstance().attach(statsStorage)``) and the
``TrainModule`` overview (score chart, model info, system tab) — re-done as a
dependency-free stdlib HTTP server: JSON endpoints + one self-contained HTML
page with inline SVG charts.

Endpoints:
  GET  /                    dashboard page
  GET  /api/sessions        session ids
  GET  /api/overview?sid=   score series + timing + memory
  GET  /api/static?sid=     model/static info
  GET  /api/histograms?sid= latest param/update histograms + norm series
                            (parity: HistogramModule)
  GET  /api/flow?sid=       network topology nodes+edges from config JSON
                            (parity: FlowListenerModule)
  GET  /api/activations?sid= latest conv activation grid
                            (parity: ConvolutionalListenerModule)
  GET  /api/tsne?sid=       stored t-SNE embedding (parity: TsneModule)
  GET  /metrics             Prometheus text exposition of the attached
                            metrics registry (process default unless one
                            is passed to UIServer)
  GET  /debug/flightrecorder the process flight recorder's event ring
                            (util/flightrecorder.py)
  GET  /debug/timeline      the process-default tracer's traces, nested
                            by parentage (util/timeline.py); optional
                            ?trace_id= filter. Requests carrying a
                            ``traceparent`` header join the caller's
                            trace (one ui.request span, header echoed)
  GET  /debug/health        training-health telemetry (util/health.py):
                            latest rule report, stats snapshot, and NaN
                            layer-of-origin attribution
  POST /profile?seconds=N   capture a jax.profiler device trace for N
                            seconds (409 while one is in progress) —
                            profile the TRAINING process the dashboard
                            watches without touching its code
  POST /api/tsne            upload coords, or raw vectors to embed
  POST /api/remote          receive stats records POSTed by
                            RemoteUIStatsStorageRouter from other hosts
                            (parity: RemoteReceiverModule)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from ..storage.stats_storage import StatsStorage
from ..util import metrics as _metrics
from ..util import tracing as _tracing

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body{font-family:sans-serif;margin:2em;background:#fafafa}
 h1{font-size:1.3em} .card{background:#fff;border:1px solid #ddd;
 border-radius:6px;padding:1em;margin-bottom:1em;max-width:900px}
 svg{width:100%;height:260px} pre{white-space:pre-wrap}
</style></head><body>
<h1>deeplearning4j_tpu — training overview</h1>
<div class="card"><b>Session:</b> <select id="sid"></select></div>
<div class="card"><b>Score vs iteration</b><svg id="score"></svg></div>
<div class="card"><b>Iteration time (ms)</b><svg id="timing"></svg></div>
<div class="card"><b>Parameter histograms</b> (latest snapshot)
 <div id="hists"></div></div>
<div class="card"><b>Update:param ratio (log10)</b><svg id="ratios"></svg>
 <div id="ratio_legend" style="font-size:11px"></div></div>
<div class="card"><b>Network flow</b><svg id="flow" style="height:auto"></svg></div>
<div class="card"><b>Conv activations</b> (latest probe)
 <div id="acts" style="font-size:11px"></div></div>
<div class="card"><b>t-SNE</b> (uploaded / embedded points)
 <svg id="tsne" style="height:420px"></svg></div>
<div class="card"><b>Model</b><pre id="model"></pre></div>
<script>
async function j(u){return (await fetch(u)).json()}
function line(svg, xs, ys, color){
  const el=document.getElementById(svg); el.innerHTML='';
  if(!xs.length) return;
  const W=900,H=260,P=35;
  const xmin=Math.min(...xs),xmax=Math.max(...xs)||1;
  const ymin=Math.min(...ys),ymax=Math.max(...ys)||1;
  const sx=x=>P+(x-xmin)/(xmax-xmin||1)*(W-2*P);
  const sy=y=>H-P-(y-ymin)/(ymax-ymin||1)*(H-2*P);
  let d='M'+xs.map((x,i)=>sx(x)+','+sy(ys[i])).join(' L');
  el.innerHTML=`<path d="${d}" fill="none" stroke="${color}" stroke-width="1.5"/>
   <text x="5" y="15" font-size="11">${ymax.toPrecision(4)}</text>
   <text x="5" y="${H-8}" font-size="11">${ymin.toPrecision(4)}</text>`;
}
function multiline(svgId, series, legendId){
  const el=document.getElementById(svgId); el.innerHTML='';
  const names=Object.keys(series); if(!names.length) return;
  const W=900,H=260,P=35;
  const colors=['#1565c0','#e65100','#2e7d32','#c62828','#6a1b9a',
                '#00838f','#f9a825','#4e342e'];
  let xmin=1e99,xmax=-1e99,ymin=1e99,ymax=-1e99;
  for(const n of names){
    for(const x of series[n].xs){xmin=Math.min(xmin,x);xmax=Math.max(xmax,x)}
    for(const y of series[n].ys){ymin=Math.min(ymin,y);ymax=Math.max(ymax,y)}
  }
  const sx=x=>P+(x-xmin)/(xmax-xmin||1)*(W-2*P);
  const sy=y=>H-P-(y-ymin)/(ymax-ymin||1)*(H-2*P);
  let out='',leg='';
  names.forEach((n,i)=>{
    const c=colors[i%colors.length], s=series[n];
    out+=`<path d="M${s.xs.map((x,k)=>sx(x)+','+sy(s.ys[k])).join(' L')}"
      fill="none" stroke="${c}" stroke-width="1.2"/>`;
    leg+=`<span style="color:${c}">&#9632; ${n}</span> `;
  });
  out+=`<text x="5" y="15" font-size="11">${ymax.toPrecision(3)}</text>
   <text x="5" y="${H-8}" font-size="11">${ymin.toPrecision(3)}</text>`;
  el.innerHTML=out;
  if(legendId) document.getElementById(legendId).innerHTML=leg;
}
function histSvg(h, color){
  const W=280,H=90,n=h.counts.length;
  const m=Math.max(...h.counts)||1;
  let rects='';
  h.counts.forEach((c,i)=>{
    const bh=(c/m)*(H-18);
    rects+=`<rect x="${i*W/n}" y="${H-16-bh}" width="${W/n-1}"
      height="${bh}" fill="${color}"/>`;
  });
  return `<svg style="width:${W}px;height:${H}px">${rects}
    <text x="0" y="${H-3}" font-size="9">${h.min.toPrecision(3)}</text>
    <text x="${W-55}" y="${H-3}" font-size="9">${h.max.toPrecision(3)}</text>
  </svg>`;
}
async function refresh(){
  const sid=document.getElementById('sid').value;
  if(!sid) return;
  const o=await j('/api/overview?sid='+sid);
  line('score', o.iterations, o.scores, '#1565c0');
  line('timing', o.iterations.slice(1), o.timings.slice(1), '#e65100');
  const hg=await j('/api/histograms?sid='+sid);
  const hd=document.getElementById('hists'); hd.innerHTML='';
  if(hg.latest.parameters){
    for(const [name,entry] of Object.entries(hg.latest.parameters)){
      let cell=`<div style="display:inline-block;margin:4px;
        vertical-align:top"><div style="font-size:11px">${name}
        &nbsp;|W|=${entry.norm.toPrecision(3)}</div>`;
      cell+=histSvg(entry.histogram,'#1565c0');
      if(entry.update) cell+=histSvg(entry.update.histogram,'#e65100');
      hd.innerHTML+=cell+'</div>';
    }
  }
  const series={};
  for(const [name,s] of Object.entries(hg.norm_series)){
    const ys=s.update_ratios.map(r=>r>0?Math.log10(r):-10);
    if(s.iterations.length>1) series[name]={xs:s.iterations, ys:ys};
  }
  multiline('ratios', series, 'ratio_legend');
  const s=await j('/api/static?sid='+sid);
  document.getElementById('model').textContent=JSON.stringify(s,null,1);
  flowChart(await j('/api/flow?sid='+sid));
  actGrid(await j('/api/activations?sid='+sid));
  tsneChart(await j('/api/tsne?sid='+sid));
}
function flowChart(g){
  const el=document.getElementById('flow'); el.innerHTML='';
  if(!g.nodes.length) return;
  // layered left-to-right layout: depth = longest path from an input
  const depth={};
  g.nodes.forEach(n=>{depth[n.id]=0});
  for(let pass=0;pass<g.nodes.length;pass++)
    g.edges.forEach(([a,b])=>{depth[b]=Math.max(depth[b],depth[a]+1)});
  const cols={};
  g.nodes.forEach(n=>{(cols[depth[n.id]]=cols[depth[n.id]]||[]).push(n)});
  const BW=150,BH=30,GX=40,GY=12,pos={};
  let maxRow=1,maxCol=0;
  Object.entries(cols).forEach(([d,ns])=>{
    maxRow=Math.max(maxRow,ns.length); maxCol=Math.max(maxCol,+d);
    ns.forEach((n,i)=>{pos[n.id]=[8+d*(BW+GX), 8+i*(BH+GY)]});
  });
  const H=16+maxRow*(BH+GY), W=16+(maxCol+1)*(BW+GX);
  let out='';
  g.edges.forEach(([a,b])=>{
    const [x1,y1]=pos[a],[x2,y2]=pos[b];
    out+=`<line x1="${x1+BW}" y1="${y1+BH/2}" x2="${x2}" y2="${y2+BH/2}"
      stroke="#999" marker-end="url(#arr)"/>`;
  });
  g.nodes.forEach(n=>{
    const [x,y]=pos[n.id];
    const c=n.kind==='input'?'#e8f0d8':'#dce8f8';
    out+=`<rect x="${x}" y="${y}" width="${BW}" height="${BH}" rx="4"
      fill="${c}" stroke="#667"/>
      <text x="${x+6}" y="${y+19}" font-size="10">${n.label}</text>`;
  });
  // viewBox + height so deep DAGs (ResNet-50 ~50 columns) scale to the
  // card width instead of clipping at it
  el.setAttribute('height',H);
  el.setAttribute('viewBox',`0 0 ${W} ${H}`);
  el.setAttribute('preserveAspectRatio','xMinYMin meet');
  el.innerHTML='<defs><marker id="arr" markerWidth="8" markerHeight="8" '+
    'refX="7" refY="3" orient="auto"><path d="M0,0 L7,3 L0,6 z" '+
    'fill="#999"/></marker></defs>'+out;
}
function actGrid(a){
  const el=document.getElementById('acts');
  if(!a.maps){el.innerHTML='(no activation records — attach a '+
    'ConvolutionalIterationListener)'; return;}
  el.innerHTML=`layer ${a.layer} @ iteration ${a.iteration}, `+
    `shape ${a.shape.join('x')}<br>`;
  a.maps.forEach(m=>{
    const h=m.length,w=m[0].length,cell=Math.max(1,Math.floor(64/w));
    const cv=document.createElement('canvas');
    cv.width=w*cell; cv.height=h*cell; cv.style.margin='2px';
    const ctx=cv.getContext('2d');
    m.forEach((row,y)=>row.forEach((v,x)=>{
      const g=Math.round(v*255);
      ctx.fillStyle=`rgb(${g},${g},${g})`;
      ctx.fillRect(x*cell,y*cell,cell,cell);
    }));
    el.appendChild(cv);
  });
}
function tsneChart(t){
  const el=document.getElementById('tsne'); el.innerHTML='';
  if(!t.coords||!t.coords.length) return;
  const W=900,H=420,P=20;
  const xs=t.coords.map(c=>c[0]),ys=t.coords.map(c=>c[1]);
  const xmin=Math.min(...xs),xmax=Math.max(...xs);
  const ymin=Math.min(...ys),ymax=Math.max(...ys);
  const sx=x=>P+(x-xmin)/(xmax-xmin||1)*(W-2*P);
  const sy=y=>H-P-(y-ymin)/(ymax-ymin||1)*(H-2*P);
  const colors=['#1565c0','#e65100','#2e7d32','#c62828','#6a1b9a',
                '#00838f','#f9a825','#4e342e'];
  let labelIdx={},next=0,out='';
  t.coords.forEach((c,i)=>{
    let col='#1565c0';
    if(t.labels){
      const l=t.labels[i];
      if(!(l in labelIdx)) labelIdx[l]=next++;
      col=colors[labelIdx[l]%colors.length];
    }
    out+=`<circle cx="${sx(c[0])}" cy="${sy(c[1])}" r="2.5"
      fill="${col}" fill-opacity="0.7"/>`;
  });
  el.innerHTML=out;
}
async function init(){
  const sessions=await j('/api/sessions');
  const sel=document.getElementById('sid');
  sel.innerHTML=sessions.map(s=>`<option>${s}</option>`).join('');
  sel.onchange=refresh; refresh(); setInterval(refresh, 3000);
}
init();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    storage: StatsStorage = None
    registry: Optional[_metrics.MetricsRegistry] = None

    def log_message(self, *args):  # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # header in → header out: the caller's trace context (or the
        # ui.request span opened under it) rides the response back
        tp = getattr(self, "_traceparent_out", None) \
            or self.headers.get("traceparent")
        if tp:
            self.send_header("traceparent", tp)
        self.end_headers()
        self.wfile.write(body)

    def _traced(self, method):
        """Dashboard requests carrying a ``traceparent`` header join the
        caller's trace: one ``ui.request`` span in the process-default
        tracer, its context echoed in the response header."""
        ctx = _tracing.extract(self.headers.get("traceparent"))
        if ctx is None:
            self._traceparent_out = None
            return method()
        with _tracing.TRACER.span(
                "ui.request", parent=ctx,
                attributes={"path": urlparse(self.path).path}) as span:
            self._traceparent_out = _tracing.inject(span)
            return method()

    def do_GET(self):
        return self._traced(self._handle_get)

    def _handle_get(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        st = self.storage
        if url.path == "/":
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif url.path == "/metrics":
            # Prometheus exposition: the dashboard process's registry
            # (training listeners, storage routing, phase timings)
            _metrics.write_exposition(self, self.registry
                                      or _metrics.REGISTRY)
        elif url.path == "/debug/flightrecorder":
            from ..util import flightrecorder as _flight
            self._json({"events": _flight.jsonable_events()})
        elif url.path == "/debug/timeline":
            from ..util import timeline as _timeline
            tid = q.get("trace_id", [None])[0]
            payload = {"traces": _timeline.trace_summaries(
                _tracing.TRACER, trace_id=tid)}
            self._json(json.loads(json.dumps(payload, default=repr)))
        elif url.path == "/debug/health":
            # training-health telemetry: latest rule report + stats
            # snapshot + NaN layer-of-origin attribution (util.health)
            from ..util import health as _health
            self._json(json.loads(
                json.dumps(_health.debug_payload(), default=repr)))
        elif url.path == "/api/sessions":
            self._json(st.list_session_ids())
        elif url.path == "/api/overview":
            sid = q.get("sid", [""])[0]
            iters, scores, timings = [], [], []
            for wid in st.list_workers(sid, "StatsListener"):
                for rec in st.get_all_updates_after(sid, "StatsListener",
                                                    wid, 0.0):
                    iters.append(rec.data.get("iteration"))
                    scores.append(rec.data.get("score"))
                    timings.append(rec.data.get("iteration_ms") or 0.0)
            self._json({"iterations": iters, "scores": scores,
                        "timings": timings})
        elif url.path == "/api/static":
            sid = q.get("sid", [""])[0]
            out = {}
            for wid in st.list_workers(sid, "StatsListener"):
                rec = st.get_static_info(sid, "StatsListener", wid)
                if rec:
                    out[wid] = {k: v for k, v in rec.data.items()
                                if k != "config_json"}
            self._json(out)
        elif url.path == "/api/flow":
            # network topology from the posted config JSON (parity:
            # FlowListenerModule — live network-flow diagram)
            sid = q.get("sid", [""])[0]
            self._json(self._flow_graph(sid))
        elif url.path == "/api/activations":
            # latest conv activation grid (parity:
            # ConvolutionalListenerModule)
            sid = q.get("sid", [""])[0]
            from .listeners import ACTIVATIONS_TYPE_ID
            latest = {}
            for wid in st.list_workers(sid, ACTIVATIONS_TYPE_ID):
                for rec in st.get_all_updates_after(
                        sid, ACTIVATIONS_TYPE_ID, wid, 0.0):
                    it = rec.data.get("iteration", -1)
                    if it >= latest.get("iteration", -1):
                        latest = rec.data
            self._json(latest)
        elif url.path == "/api/tsne":
            # stored t-SNE embeddings (parity: TsneModule)
            sid = q.get("sid", [""])[0]
            latest = {}
            for wid in st.list_workers(sid, "TsneModule"):
                for rec in st.get_all_updates_after(sid, "TsneModule",
                                                    wid, 0.0):
                    if rec.timestamp >= latest.get("timestamp", -1):
                        latest = {"timestamp": rec.timestamp, **rec.data}
            self._json(latest)
        elif url.path == "/api/histograms":
            # latest param histograms + per-param norm time series
            # (parity: the reference HistogramModule's data feed)
            sid = q.get("sid", [""])[0]
            workers = st.list_workers(sid, "StatsListener")
            latest, norms = {}, {}
            for wid in workers:
                for rec in st.get_all_updates_after(sid, "StatsListener",
                                                    wid, 0.0):
                    params = rec.data.get("parameters")
                    if not params:
                        continue
                    it = rec.data.get("iteration")
                    # newest snapshot across ALL workers, by iteration —
                    # not whichever worker happens to iterate last
                    if it is not None and it >= latest.get("iteration", -1):
                        latest = {"iteration": it, "worker": wid,
                                  "parameters": params}
                    for pname, entry in params.items():
                        # one series per (param, worker) so multi-worker
                        # sessions don't interleave into a zig-zag
                        key = (pname if len(workers) == 1
                               else f"{pname} [{wid}]")
                        s = norms.setdefault(key, {"iterations": [],
                                                   "norms": [],
                                                   "update_ratios": []})
                        s["iterations"].append(it)
                        s["norms"].append(entry.get("norm"))
                        s["update_ratios"].append(entry.get("update_ratio"))
            self._json({"latest": latest, "norm_series": norms})
        else:
            self._json({"error": "not found"}, 404)

    def _flow_graph(self, sid: str):
        """Nodes + edges parsed from the session's static config_json."""
        st = self.storage
        for wid in st.list_workers(sid, "StatsListener"):
            rec = st.get_static_info(sid, "StatsListener", wid)
            if not rec or "config_json" not in rec.data:
                continue
            conf = json.loads(rec.data["config_json"])
            nodes, edges = [], []
            if "vertices" in conf:  # ComputationGraph DAG
                for name in conf.get("network_inputs", []):
                    nodes.append({"id": name, "label": name, "kind": "input"})
                for name, v in conf["vertices"].items():
                    layer = (v.get("layer") or {}).get("__layer__") or {}
                    kind = layer.get("type") or v.get("type", "vertex")
                    nodes.append({"id": name, "label": f"{name} ({kind})",
                                  "kind": kind})
                for dst, srcs in conf.get("vertex_inputs", {}).items():
                    for s in srcs:
                        edges.append([s, dst])
            else:  # MultiLayerNetwork chain
                nodes.append({"id": "input", "label": "input",
                              "kind": "input"})
                prev = "input"
                for i, layer in enumerate(conf.get("layers", [])):
                    nid = layer.get("name") or f"layer_{i}"
                    nodes.append({"id": nid,
                                  "label": f"{nid} ({layer.get('type')})",
                                  "kind": layer.get("type", "layer")})
                    edges.append([prev, nid])
                    prev = nid
            return {"nodes": nodes, "edges": edges}
        return {"nodes": [], "edges": []}

    def do_POST(self):
        return self._traced(self._handle_post)

    def _handle_post(self):
        url = urlparse(self.path)
        if url.path == "/profile":
            # same contract as the inference server's /profile (one
            # capture at a time, process-wide)
            from ..util.profiling import profile_request
            body, code = profile_request(parse_qs(url.query))
            self._json(body, code)
            return
        if url.path == "/api/tsne":
            # upload coordinates, or raw vectors to embed server-side
            # (parity: TsneModule's coordinate-file upload)
            try:
                length = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(length).decode())
                sid = payload.get("sid", "default")
                labels = payload.get("labels")
                if "coords" in payload:
                    coords = payload["coords"]
                else:
                    from ..plot.tsne import BarnesHutTsne
                    import numpy as np
                    vecs = np.asarray(payload["vectors"], dtype=np.float64)
                    ts = BarnesHutTsne(
                        n_components=2,
                        perplexity=float(payload.get("perplexity", 30.0)),
                        max_iter=int(payload.get("iterations", 250)),
                        seed=int(payload.get("seed", 0)))
                    coords = np.round(ts.fit_transform(vecs), 4).tolist()
                from ..storage.stats_storage import Persistable
                import time as _time
                self.storage.put_update(Persistable(
                    session_id=sid, type_id="TsneModule",
                    worker_id="upload", timestamp=_time.time(),
                    data={"coords": coords, "labels": labels}))
                self._json({"ok": True, "n": len(coords)})
            except Exception as e:
                self._json({"error": str(e)}, 400)
            return
        if url.path != "/api/remote":
            self._json({"error": "not found"}, 404)
            return
        # receiver for RemoteUIStatsStorageRouter (parity:
        # RemoteReceiverModule) — remote/distributed runs report into the
        # attached storage exactly like local listeners
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length).decode())
            from ..storage.stats_storage import Persistable
            rec = Persistable.from_json(json.dumps(payload["record"]))
            if payload.get("kind") == "static":
                self.storage.put_static_info(rec)
            else:
                self.storage.put_update(rec)
            self._json({"ok": True})
        except Exception as e:  # malformed POSTs must not kill the server
            self._json({"error": str(e)}, 400)


class UIServer:
    """``UIServer.get_instance().attach(storage)`` then browse
    ``http://localhost:<port>`` (parity: ``api/UIServer.java``)."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        self.port = port
        # default: the process registry, so a dashboard scrape sees the
        # training process's MetricsListener / storage-routing counters
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        from .stats import register_device_memory_gauges
        register_device_memory_gauges(self.registry)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.storage: Optional[StatsStorage] = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> "UIServer":
        self.storage = storage
        if self._httpd is None:
            handler = type("BoundHandler", (_Handler,),
                           {"storage": storage, "registry": self.registry})
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.RequestHandlerClass.storage = storage
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        UIServer._instance = None
