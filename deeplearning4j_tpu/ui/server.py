"""UIServer: the training dashboard.

Parity: reference ``deeplearning4j-play/.../PlayUIServer.java`` +
``api/UIServer.java`` (``getInstance().attach(statsStorage)``) and the
``TrainModule`` overview (score chart, model info, system tab) — re-done as a
dependency-free stdlib HTTP server: JSON endpoints + one self-contained HTML
page with inline SVG charts.

Endpoints:
  GET  /                    dashboard page
  GET  /api/sessions        session ids
  GET  /api/overview?sid=   score series + timing + memory
  GET  /api/static?sid=     model/static info
  GET  /api/histograms?sid= latest param/update histograms + norm series
                            (parity: HistogramModule)
  POST /api/remote          receive stats records POSTed by
                            RemoteUIStatsStorageRouter from other hosts
                            (parity: RemoteReceiverModule)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from ..storage.stats_storage import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body{font-family:sans-serif;margin:2em;background:#fafafa}
 h1{font-size:1.3em} .card{background:#fff;border:1px solid #ddd;
 border-radius:6px;padding:1em;margin-bottom:1em;max-width:900px}
 svg{width:100%;height:260px} pre{white-space:pre-wrap}
</style></head><body>
<h1>deeplearning4j_tpu — training overview</h1>
<div class="card"><b>Session:</b> <select id="sid"></select></div>
<div class="card"><b>Score vs iteration</b><svg id="score"></svg></div>
<div class="card"><b>Iteration time (ms)</b><svg id="timing"></svg></div>
<div class="card"><b>Parameter histograms</b> (latest snapshot)
 <div id="hists"></div></div>
<div class="card"><b>Update:param ratio (log10)</b><svg id="ratios"></svg>
 <div id="ratio_legend" style="font-size:11px"></div></div>
<div class="card"><b>Model</b><pre id="model"></pre></div>
<script>
async function j(u){return (await fetch(u)).json()}
function line(svg, xs, ys, color){
  const el=document.getElementById(svg); el.innerHTML='';
  if(!xs.length) return;
  const W=900,H=260,P=35;
  const xmin=Math.min(...xs),xmax=Math.max(...xs)||1;
  const ymin=Math.min(...ys),ymax=Math.max(...ys)||1;
  const sx=x=>P+(x-xmin)/(xmax-xmin||1)*(W-2*P);
  const sy=y=>H-P-(y-ymin)/(ymax-ymin||1)*(H-2*P);
  let d='M'+xs.map((x,i)=>sx(x)+','+sy(ys[i])).join(' L');
  el.innerHTML=`<path d="${d}" fill="none" stroke="${color}" stroke-width="1.5"/>
   <text x="5" y="15" font-size="11">${ymax.toPrecision(4)}</text>
   <text x="5" y="${H-8}" font-size="11">${ymin.toPrecision(4)}</text>`;
}
function multiline(svgId, series, legendId){
  const el=document.getElementById(svgId); el.innerHTML='';
  const names=Object.keys(series); if(!names.length) return;
  const W=900,H=260,P=35;
  const colors=['#1565c0','#e65100','#2e7d32','#c62828','#6a1b9a',
                '#00838f','#f9a825','#4e342e'];
  let xmin=1e99,xmax=-1e99,ymin=1e99,ymax=-1e99;
  for(const n of names){
    for(const x of series[n].xs){xmin=Math.min(xmin,x);xmax=Math.max(xmax,x)}
    for(const y of series[n].ys){ymin=Math.min(ymin,y);ymax=Math.max(ymax,y)}
  }
  const sx=x=>P+(x-xmin)/(xmax-xmin||1)*(W-2*P);
  const sy=y=>H-P-(y-ymin)/(ymax-ymin||1)*(H-2*P);
  let out='',leg='';
  names.forEach((n,i)=>{
    const c=colors[i%colors.length], s=series[n];
    out+=`<path d="M${s.xs.map((x,k)=>sx(x)+','+sy(s.ys[k])).join(' L')}"
      fill="none" stroke="${c}" stroke-width="1.2"/>`;
    leg+=`<span style="color:${c}">&#9632; ${n}</span> `;
  });
  out+=`<text x="5" y="15" font-size="11">${ymax.toPrecision(3)}</text>
   <text x="5" y="${H-8}" font-size="11">${ymin.toPrecision(3)}</text>`;
  el.innerHTML=out;
  if(legendId) document.getElementById(legendId).innerHTML=leg;
}
function histSvg(h, color){
  const W=280,H=90,n=h.counts.length;
  const m=Math.max(...h.counts)||1;
  let rects='';
  h.counts.forEach((c,i)=>{
    const bh=(c/m)*(H-18);
    rects+=`<rect x="${i*W/n}" y="${H-16-bh}" width="${W/n-1}"
      height="${bh}" fill="${color}"/>`;
  });
  return `<svg style="width:${W}px;height:${H}px">${rects}
    <text x="0" y="${H-3}" font-size="9">${h.min.toPrecision(3)}</text>
    <text x="${W-55}" y="${H-3}" font-size="9">${h.max.toPrecision(3)}</text>
  </svg>`;
}
async function refresh(){
  const sid=document.getElementById('sid').value;
  if(!sid) return;
  const o=await j('/api/overview?sid='+sid);
  line('score', o.iterations, o.scores, '#1565c0');
  line('timing', o.iterations.slice(1), o.timings.slice(1), '#e65100');
  const hg=await j('/api/histograms?sid='+sid);
  const hd=document.getElementById('hists'); hd.innerHTML='';
  if(hg.latest.parameters){
    for(const [name,entry] of Object.entries(hg.latest.parameters)){
      let cell=`<div style="display:inline-block;margin:4px;
        vertical-align:top"><div style="font-size:11px">${name}
        &nbsp;|W|=${entry.norm.toPrecision(3)}</div>`;
      cell+=histSvg(entry.histogram,'#1565c0');
      if(entry.update) cell+=histSvg(entry.update.histogram,'#e65100');
      hd.innerHTML+=cell+'</div>';
    }
  }
  const series={};
  for(const [name,s] of Object.entries(hg.norm_series)){
    const ys=s.update_ratios.map(r=>r>0?Math.log10(r):-10);
    if(s.iterations.length>1) series[name]={xs:s.iterations, ys:ys};
  }
  multiline('ratios', series, 'ratio_legend');
  const s=await j('/api/static?sid='+sid);
  document.getElementById('model').textContent=JSON.stringify(s,null,1);
}
async function init(){
  const sessions=await j('/api/sessions');
  const sel=document.getElementById('sid');
  sel.innerHTML=sessions.map(s=>`<option>${s}</option>`).join('');
  sel.onchange=refresh; refresh(); setInterval(refresh, 3000);
}
init();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    storage: StatsStorage = None

    def log_message(self, *args):  # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        st = self.storage
        if url.path == "/":
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif url.path == "/api/sessions":
            self._json(st.list_session_ids())
        elif url.path == "/api/overview":
            sid = q.get("sid", [""])[0]
            iters, scores, timings = [], [], []
            for wid in st.list_workers(sid, "StatsListener"):
                for rec in st.get_all_updates_after(sid, "StatsListener",
                                                    wid, 0.0):
                    iters.append(rec.data.get("iteration"))
                    scores.append(rec.data.get("score"))
                    timings.append(rec.data.get("iteration_ms") or 0.0)
            self._json({"iterations": iters, "scores": scores,
                        "timings": timings})
        elif url.path == "/api/static":
            sid = q.get("sid", [""])[0]
            out = {}
            for wid in st.list_workers(sid, "StatsListener"):
                rec = st.get_static_info(sid, "StatsListener", wid)
                if rec:
                    out[wid] = {k: v for k, v in rec.data.items()
                                if k != "config_json"}
            self._json(out)
        elif url.path == "/api/histograms":
            # latest param histograms + per-param norm time series
            # (parity: the reference HistogramModule's data feed)
            sid = q.get("sid", [""])[0]
            workers = st.list_workers(sid, "StatsListener")
            latest, norms = {}, {}
            for wid in workers:
                for rec in st.get_all_updates_after(sid, "StatsListener",
                                                    wid, 0.0):
                    params = rec.data.get("parameters")
                    if not params:
                        continue
                    it = rec.data.get("iteration")
                    # newest snapshot across ALL workers, by iteration —
                    # not whichever worker happens to iterate last
                    if it is not None and it >= latest.get("iteration", -1):
                        latest = {"iteration": it, "worker": wid,
                                  "parameters": params}
                    for pname, entry in params.items():
                        # one series per (param, worker) so multi-worker
                        # sessions don't interleave into a zig-zag
                        key = (pname if len(workers) == 1
                               else f"{pname} [{wid}]")
                        s = norms.setdefault(key, {"iterations": [],
                                                   "norms": [],
                                                   "update_ratios": []})
                        s["iterations"].append(it)
                        s["norms"].append(entry.get("norm"))
                        s["update_ratios"].append(entry.get("update_ratio"))
            self._json({"latest": latest, "norm_series": norms})
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        url = urlparse(self.path)
        if url.path != "/api/remote":
            self._json({"error": "not found"}, 404)
            return
        # receiver for RemoteUIStatsStorageRouter (parity:
        # RemoteReceiverModule) — remote/distributed runs report into the
        # attached storage exactly like local listeners
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length).decode())
            from ..storage.stats_storage import Persistable
            rec = Persistable.from_json(json.dumps(payload["record"]))
            if payload.get("kind") == "static":
                self.storage.put_static_info(rec)
            else:
                self.storage.put_update(rec)
            self._json({"ok": True})
        except Exception as e:  # malformed POSTs must not kill the server
            self._json({"error": str(e)}, 400)


class UIServer:
    """``UIServer.get_instance().attach(storage)`` then browse
    ``http://localhost:<port>`` (parity: ``api/UIServer.java``)."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.storage: Optional[StatsStorage] = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> "UIServer":
        self.storage = storage
        if self._httpd is None:
            handler = type("BoundHandler", (_Handler,), {"storage": storage})
            self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.RequestHandlerClass.storage = storage
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        UIServer._instance = None
