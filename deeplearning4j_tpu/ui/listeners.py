"""Visualization listeners beyond StatsListener.

Parity: reference ``deeplearning4j-ui/.../ConvolutionalIterationListener.java``
(activation image grids for conv layers, rendered by
``ConvolutionalListenerModule``) — re-done probe-based: the TPU train step is
one compiled program, so instead of hooking eager per-layer activations the
listener re-runs ``feed_forward`` on a fixed probe batch every N iterations
and posts downsampled activation maps to stats storage, where the UI's
activations module renders them.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from ..storage.stats_storage import Persistable, StatsStorageRouter
from ..optimize.listeners import TrainingListener

ACTIVATIONS_TYPE_ID = "ConvolutionalListener"


class ConvolutionalIterationListener(TrainingListener):
    """Posts activation-map grids for the first convolutional (4-D NHWC)
    activation every ``frequency`` iterations.

    ``probe_input``: a fixed input batch (only the first example is used) so
    successive grids are comparable across training, like the reference's
    last-minibatch capture but deterministic.
    """

    def __init__(self, router: StatsStorageRouter, probe_input,
                 frequency: int = 25, session_id: str = "default",
                 worker_id: str = "worker_0", max_channels: int = 16,
                 max_size: int = 28):
        self.router = router
        self.probe = np.asarray(probe_input)[:1]
        self.frequency = max(1, int(frequency))
        self.session_id = session_id
        self.worker_id = worker_id
        self.max_channels = int(max_channels)
        self.max_size = int(max_size)

    def iteration_done(self, model, iteration: int, score) -> None:
        if iteration % self.frequency:
            return
        acts = model.feed_forward(self.probe, train=False)
        if isinstance(acts, dict):  # ComputationGraph: name → activation
            items = list(acts.items())
        else:
            # MLN list has the input at index 0 (feedForward parity) —
            # render layer OUTPUTS, like the reference listener
            items = [(f"layer_{i}", a) for i, a in enumerate(acts[1:])]
        for name, a in items:
            a = np.asarray(a)
            if a.ndim != 4:  # NHWC conv activation
                continue
            self._post(name, a[0], iteration)
            return  # first conv layer only, like the reference default

    def _post(self, layer_name: str, hwc: np.ndarray, iteration: int) -> None:
        h, w, c = hwc.shape
        sh = max(1, h // self.max_size)
        sw = max(1, w // self.max_size)
        maps = []
        for ch in range(min(c, self.max_channels)):
            m = hwc[::sh, ::sw, ch].astype(np.float64)
            lo, hi = float(m.min()), float(m.max())
            scale = (hi - lo) or 1.0
            maps.append(np.round((m - lo) / scale, 3).tolist())
        self.router.put_update(Persistable(
            session_id=self.session_id, type_id=ACTIVATIONS_TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(),
            data={"iteration": int(iteration), "layer": layer_name,
                  "shape": [int(h), int(w), int(c)], "maps": maps}))
