"""Observability UI: StatsListener → StatsStorage → web dashboard.

Parity: reference ``deeplearning4j-ui-parent`` — ``StatsListener.java:47``
(score/timing/memory/param-histogram collection), Play-framework ``UIServer``
with train-overview module. Here: stdlib ``http.server`` dashboard (no Play,
no SBE codecs — JSON over HTTP).
"""

from .server import UIServer
from .stats import StatsListener
from .listeners import ConvolutionalIterationListener
from . import components

__all__ = ["StatsListener", "UIServer", "ConvolutionalIterationListener",
           "components"]
