"""deeplearning4j_tpu — a TPU-native deep learning framework.

A from-scratch JAX/XLA/Pallas framework with the capabilities of Deeplearning4j
(reference: /root/reference @ 0.6.1/0.7.2-SNAPSHOT era): serializable layer/graph
configuration DSL, sequential and DAG network runtimes, pluggable updaters with
LR schedules and gradient clipping, dataset pipelines with prefetch, evaluation,
early stopping, checkpoint/resume, observability, embedding models, Keras import,
and distributed data/tensor/sequence parallelism over TPU meshes.

Architecture (TPU-first, NOT a port):
  - All layer forward passes are pure functions; backprop is ``jax.grad`` —
    replacing the reference's hand-written ``Layer.backpropGradient`` pairs
    (e.g. reference ``nn/layers/BaseLayer.java:143-167``).
  - Parameters are pytrees, not flattened buffers (reference
    ``MultiLayerNetwork.java:368`` flattenedParams); XLA fuses and donates.
  - Distribution is ``jax.sharding.Mesh`` + collectives over ICI/DCN —
    replacing Spark parameter averaging (reference
    ``ParameterAveragingTrainingMaster.java``) and ``ParallelWrapper``.
"""

__version__ = "0.1.0"

# Lazy module surface: keep `import deeplearning4j_tpu` light.
_SUBMODULES = {
    "nn", "optimize", "eval", "data", "datasets", "parallel", "models",
    "nlp", "graph", "modelimport", "ui", "util", "ops", "losses", "dtypes",
    "rng", "earlystopping", "clustering", "plot", "storage", "gradientcheck",
}


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib
        try:
            mod = importlib.import_module(f"{__name__}.{name}")
        except ModuleNotFoundError as e:
            if e.name != f"{__name__}.{name}":
                raise  # a real dependency is missing inside the submodule
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
