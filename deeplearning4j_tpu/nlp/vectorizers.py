"""Bag-of-words / TF-IDF text vectorizers.

Parity: reference ``bagofwords/vectorizer/`` — ``TextVectorizer`` interface
(``TextVectorizer.java:35``: fit → vocab, ``transform(text)`` → vector,
``vectorize(text, label)`` → DataSet), ``BagOfWordsVectorizer.java`` (raw
counts) and ``TfidfVectorizer.java`` (count × idf weighting, idf from
document frequencies).

TPU-native note: vectorization is host-side ETL (numpy); the output feeds
``MultiLayerNetwork.fit`` as dense [docs, vocab] arrays. Count sparsity
doesn't pay on MXU matmuls at DL4J-era vocab sizes.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..datasets.dataset import DataSet
from .documents import LabelAwareIterator, LabelsSource
from .tokenization import DefaultTokenizerFactory, TokenizerFactory


class TextVectorizer:
    """Shared fit/transform machinery (parity: ``BaseTextVectorizer.java``).

    fit() builds the vocabulary (min_word_frequency filter, stop words) and
    document frequencies from a LabelAwareIterator or an iterable of strings.
    """

    def __init__(self, *, tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1,
                 stop_words: Optional[Iterable[str]] = None):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = max(1, int(min_word_frequency))
        self.stop_words = frozenset(stop_words or ())
        self.vocab: Dict[str, int] = {}
        self.doc_freq: Dict[str, int] = {}
        self.n_docs = 0
        self.labels_source = LabelsSource()

    # ------------------------------------------------------------------

    def _tokens(self, text: str) -> List[str]:
        toks = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    def _documents(self, source):
        if isinstance(source, LabelAwareIterator):
            self.labels_source = source.labels_source
            for doc in source:
                yield doc.content
        else:
            for item in source:
                if hasattr(item, "content"):
                    for l in item.labels:
                        self.labels_source.store_label(l)
                    yield item.content
                else:
                    yield item

    def fit(self, source) -> "TextVectorizer":
        counts: Counter = Counter()
        dfs: Counter = Counter()
        n = 0
        for content in self._documents(source):
            toks = self._tokens(content)
            counts.update(toks)
            dfs.update(set(toks))
            n += 1
        self.n_docs = n
        words = sorted(w for w, c in counts.items()
                       if c >= self.min_word_frequency)
        self.vocab = {w: i for i, w in enumerate(words)}
        self.doc_freq = {w: dfs[w] for w in words}
        return self

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def index_of(self, word: str) -> int:
        return self.vocab.get(word, -1)

    # ------------------------------------------------------------------

    def _weight(self, count: int, word: str, doc_len: int) -> float:
        raise NotImplementedError

    def transform(self, text: str) -> np.ndarray:
        """One text → [vocab] weight vector (parity: ``transform``)."""
        if not self.vocab:
            raise ValueError("call fit() first")
        toks = self._tokens(text)
        out = np.zeros((self.vocab_size,), dtype=np.float32)
        for w, c in Counter(toks).items():
            i = self.vocab.get(w, -1)
            if i >= 0:
                out[i] = self._weight(c, w, len(toks))
        return out

    def transform_documents(self, texts: Sequence[str]) -> np.ndarray:
        return np.stack([self.transform(t) for t in texts])

    def vectorize(self, text: str, label: str) -> DataSet:
        """text + label → DataSet row (parity: ``vectorize(String, String)``,
        ``TfidfVectorizer.java:66``)."""
        x = self.transform(text)[None, :]
        idx = self.labels_source.index_of(label)
        if idx < 0:
            self.labels_source.store_label(label)
            idx = self.labels_source.index_of(label)
        y = np.zeros((1, max(1, self.labels_source.size())), dtype=np.float32)
        y[0, idx] = 1.0
        return DataSet(x, y)

    def fit_transform(self, source) -> DataSet:
        """Fit on a LabelAwareIterator and return the full [docs, vocab] /
        [docs, labels] design matrix as one DataSet."""
        docs: List[str] = []
        labels: List[Optional[str]] = []
        if isinstance(source, LabelAwareIterator):
            self.labels_source = source.labels_source
            for d in source:
                docs.append(d.content)
                labels.append(d.label)
        else:
            for item in source:
                if hasattr(item, "content"):
                    docs.append(item.content)
                    labels.append(item.label)
                    for l in item.labels:
                        self.labels_source.store_label(l)
                else:
                    docs.append(item)
                    labels.append(None)
        self.fit(docs)
        x = self.transform_documents(docs)
        n_lab = max(1, self.labels_source.size())
        y = np.zeros((len(docs), n_lab), dtype=np.float32)
        for r, l in enumerate(labels):
            if l is not None:
                y[r, self.labels_source.index_of(l)] = 1.0
        return DataSet(x, y)


class BagOfWordsVectorizer(TextVectorizer):
    """Raw term counts (parity: ``BagOfWordsVectorizer.java``)."""

    def _weight(self, count: int, word: str, doc_len: int) -> float:
        return float(count)


class TfidfVectorizer(TextVectorizer):
    """count × idf weighting, idf = log(n_docs / df) (parity:
    ``TfidfVectorizer.java`` via ``MathUtils.idf``; +1 smoothing guards
    unseen/degenerate df)."""

    def idf(self, word: str) -> float:
        df = self.doc_freq.get(word, 0)
        if df == 0 or self.n_docs == 0:
            return 0.0
        return math.log(self.n_docs / df) + 1.0

    def _weight(self, count: int, word: str, doc_len: int) -> float:
        return float(count) * self.idf(word)
