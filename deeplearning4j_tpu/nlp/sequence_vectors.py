"""SequenceVectors: the generic embedding trainer over token sequences.

Parity: reference ``models/sequencevectors/SequenceVectors.java:161``
(``fit()``: vocab build → training threads → per-sequence ``trainSequence``)
with the Hogwild thread pool (``:245-260``) replaced by host-side batch
preparation + jitted vectorized update steps (see learning.py).

Also the base for Word2Vec / ParagraphVectors / DeepWalk, exactly as in the
reference's class hierarchy.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from . import learning as _learning
from .vocab import Huffman, VocabCache, VocabConstructor


class SequenceVectors:
    """Train word/sequence embeddings from an iterable of token sequences.

    Key hyperparameters mirror the reference builder: ``layer_size``
    (vector dim), ``window``, ``negative`` (0 → hierarchical softmax),
    ``min_word_frequency``, ``sample`` (frequent-word subsampling),
    ``learning_rate``/``min_learning_rate`` (linear decay), ``epochs``,
    ``use_cbow`` (elements algo: skip-gram default), ``seed``.
    """

    def __init__(self, *, layer_size: int = 100, window: int = 5,
                 negative: int = 5, min_word_frequency: int = 1,
                 sample: float = 0.0, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, epochs: int = 1,
                 batch_size: int = 4096, use_cbow: bool = False,
                 seed: int = 42, vocab_limit: Optional[int] = None,
                 mesh=None):
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.min_word_frequency = min_word_frequency
        self.sample = sample
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.use_cbow = use_cbow
        self.seed = seed
        self.vocab_limit = vocab_limit
        # data-parallel training: pair batches sharded over mesh's "data"
        # axis, params replicated, grads all-reduced by XLA (parity role:
        # dl4j-spark-nlp's distributed Word2Vec; see
        # learning.make_sharded_ns_step). NS mode only.
        self.mesh = mesh
        self._sharded_step = None

        self.vocab: Optional[VocabCache] = None
        self.params: Optional[Dict] = None
        self._codes = self._points = self._lengths = None
        self._neg_table: Optional[np.ndarray] = None
        self._syn0_normed: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    # vocab + fit
    # ------------------------------------------------------------------

    def build_vocab(self, sequences: Iterable[List[str]]) -> None:
        self.vocab = VocabConstructor(
            self.min_word_frequency, self.vocab_limit).build(sequences)
        if self.negative <= 0:
            h = Huffman(self.vocab)
            h.apply()
            self._codes, self._points, self._lengths = h.padded_tables()
        else:
            self._neg_table = _learning.build_unigram_table(
                self.vocab.counts_array())

    def _init_params(self, extra_vectors: int = 0) -> None:
        V = self.vocab.num_words()
        self.params = _learning.init_params(
            V, self.layer_size, seed=self.seed,
            hs_nodes=(V - 1 if self.negative <= 0 else 0),
            use_neg=self.negative > 0,
            extra_vectors=extra_vectors)

    def fit(self, sequences: Iterable[List[str]],
            resettable: bool = True) -> "SequenceVectors":
        """Build vocab (if absent) + train. For multiple epochs `sequences`
        must be re-iterable (e.g. a list or SentenceIterator)."""
        seqs = sequences if not hasattr(sequences, "__next__") else list(sequences)
        if self.vocab is None:
            self.build_vocab(seqs)
        if self.params is None:
            self._init_params()
        self._train(seqs)
        self._syn0_normed = None
        return self

    # ------------------------------------------------------------------
    # training loop: host-side batching + jitted steps
    # ------------------------------------------------------------------

    def _indexed(self, seqs: Iterable[List[str]], rng: np.random.Generator
                 ) -> Iterable[np.ndarray]:
        """Token sequences → filtered index arrays (+ subsampling)."""
        vocab = self.vocab
        total = max(vocab.total_word_count, 1)
        sample = self.sample
        for seq in seqs:
            idx = [vocab.index_of(t) for t in seq]
            idx = np.array([i for i in idx if i >= 0], dtype=np.int32)
            if sample > 0 and len(idx):
                freqs = vocab.counts_array()[idx] / total
                # word2vec subsampling keep probability
                keep_p = np.minimum(
                    (np.sqrt(freqs / sample) + 1) * sample / freqs, 1.0)
                idx = idx[rng.random(len(idx)) < keep_p]
            if len(idx) >= 2:
                yield idx

    def _pairs(self, seqs, rng) -> Iterable[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
        """Yield (center, target, ctx, ctx_mask) batches. For skip-gram the
        (center→target) pairs; for CBOW ctx is the padded window.

        Vectorized per sequence (the per-position/per-window Python loops
        capped host pair production well below what the device step
        consumes — PERF.md r4 measured the jitted NS step at 6.0M
        pairs/s). Bit-exact with the original generator: the per-position
        reduced-window draw consumes the SAME rng stream, pairs appear in
        the same (position-major, ascending-j) order, and batch
        boundaries fall after the same positions — so seeded training
        runs are unchanged (see tests/test_nlp.py parity test).
        """
        W = self.window
        B = self.batch_size
        # ascending-j offsets: positions j = pos + off, off in [-W..-1, 1..W]
        offs = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
        pend: List[Tuple[np.ndarray, ...]] = []   # sub-B leftovers
        pend_n = 0

        def _flush(chunks):
            parts = [np.concatenate([c[i] for c in chunks])
                     for i in range(len(chunks[0]))]
            if self.use_cbow:
                return tuple(parts)
            c = parts[0]
            return (c, parts[1], np.zeros((len(c), 1), dtype=np.int32),
                    np.ones((len(c), 1), dtype=np.float32))

        for idx in self._indexed(seqs, rng):
            n = len(idx)
            red = rng.integers(1, W + 1, size=n)  # reduced window per position
            j = np.arange(n)[:, None] + offs[None, :]
            valid = ((np.abs(offs)[None, :] <= red[:, None])
                     & (j >= 0) & (j < n))
            if self.use_cbow:
                keep = valid.any(axis=1)
                # left-pack each row's window ids (stable sort: valid
                # entries first, original ascending-j order preserved)
                order = np.argsort(~valid, axis=1, kind="stable")
                vm = np.take_along_axis(valid, order, axis=1)
                jj = np.take_along_axis(j, order, axis=1)
                ctx = np.where(vm, idx[np.clip(jj, 0, n - 1)],
                               np.int32(0)).astype(np.int32)
                arrays = (idx[keep].astype(np.int32),
                          idx[keep].astype(np.int32),
                          ctx[keep], vm[keep].astype(np.float32))
                cnt = keep.astype(np.int64)
            else:
                cnt = valid.sum(axis=1)
                arrays = (np.repeat(idx, cnt).astype(np.int32),
                          idx[j[valid]].astype(np.int32))
            cum = np.cumsum(cnt)
            pair_off = np.concatenate([[0], cum])
            emitted = 0
            while True:
                # first position where the accumulated count crosses B —
                # the original loop emitted right after that position
                carry = pend_n if not emitted else 0
                p = int(np.searchsorted(cum, B - carry + emitted, "left"))
                if p >= n:
                    break
                end = int(pair_off[p + 1])
                chunk = tuple(a[emitted:end] for a in arrays)
                yield _flush(pend + [chunk] if pend else [chunk])
                pend, pend_n = [], 0
                emitted = end
            total = int(cum[-1]) if n else 0
            if emitted < total:
                pend.append(tuple(a[emitted:total] for a in arrays))
                pend_n += total - emitted
        if pend_n:
            yield _flush(pend)

    def _train(self, seqs) -> None:
        import jax.numpy as jnp

        rng = np.random.default_rng(self.seed)
        # one pass to count total batches for the linear LR decay
        approx_total = None
        step_i = 0
        for epoch in range(self.epochs):
            for batch in self._pairs(seqs, rng):
                center, target, ctx, ctx_mask = batch
                frac = (step_i / approx_total) if approx_total else \
                    (epoch / max(self.epochs, 1))
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1.0 - frac))
                if self.negative > 0:
                    negs = self._draw_negatives(rng, target)
                    if self.mesh is not None:
                        n_dev = self.mesh.shape["data"]
                        keep = (len(center) // n_dev) * n_dev
                        if keep == 0:
                            continue  # tail smaller than the mesh: skip
                        if self._sharded_step is None:
                            self._sharded_step = \
                                _learning.make_sharded_ns_step(
                                    self.mesh, cbow=self.use_cbow)
                        self.params, _ = self._sharded_step(
                            self.params, jnp.asarray(center[:keep]),
                            jnp.asarray(target[:keep]),
                            jnp.asarray(negs[:keep]),
                            jnp.asarray(ctx[:keep]),
                            jnp.asarray(ctx_mask[:keep]), jnp.float32(lr))
                    else:
                        self.params, _ = _learning.ns_step(
                            self.params, jnp.asarray(center),
                            jnp.asarray(target),
                            jnp.asarray(negs), jnp.asarray(ctx),
                            jnp.asarray(ctx_mask), jnp.float32(lr),
                            cbow=self.use_cbow)
                else:
                    codes = self._codes[target]
                    points = self._points[target]
                    L = self._lengths[target]
                    cmask = (np.arange(codes.shape[1])[None, :]
                             < L[:, None]).astype(np.float32)
                    self.params, _ = _learning.hs_step(
                        self.params, jnp.asarray(center), jnp.asarray(codes),
                        jnp.asarray(points), jnp.asarray(cmask),
                        jnp.asarray(ctx), jnp.asarray(ctx_mask),
                        jnp.float32(lr), cbow=self.use_cbow)
                step_i += 1
            if approx_total is None:
                approx_total = max(step_i * self.epochs, 1)

    def _draw_negatives(self, rng, target: np.ndarray) -> np.ndarray:
        K = self.negative
        draws = self._neg_table[
            rng.integers(0, len(self._neg_table), size=(len(target), K))]
        # avoid sampling the positive target (word2vec redraws; we remap to a
        # random other word which is equivalent in expectation)
        clash = draws == target[:, None]
        if clash.any():
            draws = np.where(clash, (draws + 1) % self.vocab.num_words(), draws)
        return draws.astype(np.int32)

    # ------------------------------------------------------------------
    # lookup API (parity: WordVectors/BasicModelUtils)
    # ------------------------------------------------------------------

    def _syn0(self) -> np.ndarray:
        return np.asarray(self.params["syn0"])[:self.vocab.num_words()]

    def _normed(self) -> np.ndarray:
        if self._syn0_normed is None:
            s = self._syn0()
            n = np.linalg.norm(s, axis=1, keepdims=True)
            self._syn0_normed = s / np.maximum(n, 1e-12)
        return self._syn0_normed

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.index_of(word) >= 0

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self._syn0()[i]

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        n = self._normed()
        return float(n[ia] @ n[ib])

    def words_nearest(self, word_or_vec, top: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            i = self.vocab.index_of(word_or_vec)
            if i < 0:
                return []
            vec = self._normed()[i]
            exclude = {i}
        else:
            vec = np.asarray(word_or_vec, dtype=np.float32)
            vec = vec / max(np.linalg.norm(vec), 1e-12)
            exclude = set()
        sims = self._normed() @ vec
        order = np.argsort(-sims)
        out = []
        for j in order:
            if j in exclude:
                continue
            out.append(self.vocab.word_for(int(j)))
            if len(out) >= top:
                break
        return out
