"""ParagraphVectors: document embeddings (PV-DBOW / PV-DM).

Parity: reference ``models/paragraphvectors/ParagraphVectors.java``
(labelled-document training, ``inferVector`` for unseen docs) with the
``sequence/DBOW.java`` / ``DM.java`` learning algorithms.

TPU-native: doc vectors are EXTRA rows of ``syn0`` (indices
``vocab_size + doc_id``), so the same jitted ns_step trains them:
- PV-DBOW: (center=doc_row → target=word) pairs — exactly skip-gram with the
  doc row as the center.
- PV-DM: CBOW with the doc row appended to every context window.
``infer_vector`` freezes word/output tables and SGD-fits one new row.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from . import learning as _learning
from .sequence_vectors import SequenceVectors
from .tokenization import DefaultTokenizerFactory, TokenizerFactory


class ParagraphVectors(SequenceVectors):
    def __init__(self, *, dm: bool = False, **kw):
        kw.setdefault("negative", 5)
        super().__init__(use_cbow=dm, **kw)
        self.dm = dm
        self.labels: List[str] = []
        self._label_index: Dict[str, int] = {}

    # ------------------------------------------------------------------

    def fit_documents(self, documents: Sequence[Tuple[str, List[str]]]
                      ) -> "ParagraphVectors":
        """documents: [(label, tokens)]."""
        self.labels = [lbl for lbl, _ in documents]
        self._label_index = {l: i for i, l in enumerate(self.labels)}
        token_seqs = [toks for _, toks in documents]
        self.build_vocab(token_seqs)
        self._init_params(extra_vectors=len(documents))
        self._train_docs(documents)
        self._syn0_normed = None
        return self

    def _train_docs(self, documents) -> None:
        import jax.numpy as jnp

        rng = np.random.default_rng(self.seed)
        V = self.vocab.num_words()
        W = self.window
        B = self.batch_size
        for epoch in range(self.epochs):
            lr = max(self.min_learning_rate,
                     self.learning_rate * (1 - epoch / max(self.epochs, 1)))
            centers, targets, ctxs, masks = [], [], [], []
            for doc_id, (_, toks) in enumerate(documents):
                idx = np.array([self.vocab.index_of(t) for t in toks
                                if self.vocab.index_of(t) >= 0], dtype=np.int32)
                if len(idx) == 0:
                    continue
                doc_row = V + doc_id
                for pos in range(len(idx)):
                    if self.dm:
                        # PV-DM: context = window words + doc row, predict word
                        b = rng.integers(1, W + 1)
                        lo, hi = max(0, pos - b), min(len(idx), pos + b + 1)
                        win = [idx[j] for j in range(lo, hi) if j != pos]
                        ctx = np.zeros(2 * W + 1, dtype=np.int32)
                        m = np.zeros(2 * W + 1, dtype=np.float32)
                        ctx[0] = doc_row
                        m[0] = 1.0
                        ctx[1:1 + len(win)] = win
                        m[1:1 + len(win)] = 1.0
                        centers.append(idx[pos])
                        targets.append(idx[pos])
                        ctxs.append(ctx)
                        masks.append(m)
                    else:
                        # PV-DBOW: doc row predicts each word
                        centers.append(doc_row)
                        targets.append(idx[pos])
                    if len(centers) >= B:
                        self._flush(centers, targets, ctxs, masks, lr, rng)
                        centers, targets, ctxs, masks = [], [], [], []
            if centers:
                self._flush(centers, targets, ctxs, masks, lr, rng)

    def _flush(self, centers, targets, ctxs, masks, lr, rng) -> None:
        import jax.numpy as jnp

        c = np.asarray(centers, dtype=np.int32)
        t = np.asarray(targets, dtype=np.int32)
        negs = self._draw_negatives(rng, t)
        if self.dm:
            ctx = np.stack(ctxs)
            m = np.stack(masks)
        else:
            ctx = np.zeros((len(c), 1), dtype=np.int32)
            m = np.ones((len(c), 1), dtype=np.float32)
        self.params, _ = _learning.ns_step(
            self.params, jnp.asarray(c), jnp.asarray(t), jnp.asarray(negs),
            jnp.asarray(ctx), jnp.asarray(m), jnp.float32(lr),
            cbow=self.dm)

    # ------------------------------------------------------------------

    def get_paragraph_vector(self, label: str) -> Optional[np.ndarray]:
        i = self._label_index.get(label)
        if i is None:
            return None
        return np.asarray(self.params["syn0"])[self.vocab.num_words() + i]

    def infer_vector(self, tokens: List[str], steps: int = 20,
                     learning_rate: Optional[float] = None,
                     seed: int = 0) -> np.ndarray:
        """Fit a fresh doc vector against frozen word/output tables
        (parity: ``ParagraphVectors.inferVector``)."""
        import jax
        import jax.numpy as jnp

        lr = learning_rate if learning_rate is not None else self.learning_rate
        idx = np.array([self.vocab.index_of(t) for t in tokens
                        if self.vocab.index_of(t) >= 0], dtype=np.int32)
        if len(idx) == 0:
            return np.zeros(self.layer_size, dtype=np.float32)
        rng = np.random.default_rng(seed)
        vec = jnp.asarray(
            (rng.random(self.layer_size, dtype=np.float32) - 0.5)
            / self.layer_size)
        syn1neg = self.params["syn1neg"]

        @jax.jit
        def step(vec, targets, negs, lr):
            def loss_fn(v):
                u_pos = jnp.take(syn1neg, targets, axis=0)
                u_neg = jnp.take(syn1neg, negs, axis=0)
                pos = jax.nn.log_sigmoid(u_pos @ v)
                neg = jax.nn.log_sigmoid(-(u_neg @ v))
                return -(jnp.sum(pos) + jnp.sum(neg)) / targets.shape[0]
            g = jax.grad(loss_fn)(vec)
            return vec - lr * g

        for s in range(steps):
            negs = self._draw_negatives(rng, idx)
            decayed = max(self.min_learning_rate, lr * (1 - s / steps))
            vec = step(vec, jnp.asarray(idx), jnp.asarray(negs),
                       jnp.float32(decayed))
        return np.asarray(vec)

    def nearest_labels(self, tokens_or_vec, top: int = 5) -> List[str]:
        """Most similar documents to an inferred vector / token list."""
        vec = (self.infer_vector(tokens_or_vec)
               if isinstance(tokens_or_vec, list) else np.asarray(tokens_or_vec))
        V = self.vocab.num_words()
        docs = np.asarray(self.params["syn0"])[V:V + len(self.labels)]
        docs = docs / np.maximum(np.linalg.norm(docs, axis=1, keepdims=True), 1e-12)
        vec = vec / max(np.linalg.norm(vec), 1e-12)
        order = np.argsort(-(docs @ vec))
        return [self.labels[int(i)] for i in order[:top]]
