"""Vocabulary: word cache, constructor scan, Huffman coding.

Parity: reference ``models/word2vec/wordstore/inmemory/AbstractCache.java``
(word↔index, frequencies, min-frequency filtering),
``VocabConstructor.java`` (corpus scan), ``models/word2vec/Huffman.java``
(codes/points for hierarchical softmax).
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class VocabWord:
    word: str
    count: int = 0
    index: int = -1
    # hierarchical-softmax path (filled by Huffman.apply)
    codes: Tuple[int, ...] = ()
    points: Tuple[int, ...] = ()


class VocabCache:
    """In-memory vocab (parity: ``AbstractCache``)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []
        self.total_word_count = 0

    def add_token(self, word: str, count: int = 1) -> None:
        vw = self._words.get(word)
        if vw is None:
            self._words[word] = VocabWord(word=word, count=count)
        else:
            vw.count += count
        self.total_word_count += count

    def finalize(self, min_word_frequency: int = 1,
                 limit: Optional[int] = None) -> None:
        """Drop rare words, assign indices by descending frequency.
        total_word_count shrinks to the RETAINED words' counts (word2vec
        convention — subsampling frequencies are relative to kept words)."""
        kept = [w for w in self._words.values()
                if w.count >= min_word_frequency]
        kept.sort(key=lambda w: (-w.count, w.word))
        if limit is not None:
            kept = kept[:limit]
        self._words = {w.word: w for w in kept}
        self._by_index = kept
        for i, w in enumerate(kept):
            w.index = i
        self.total_word_count = sum(w.count for w in kept)

    # -- lookups --
    def has_token(self, word: str) -> bool:
        return word in self._words

    def word_for(self, index: int) -> str:
        return self._by_index[index].word

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw is not None else -1

    def word_frequency(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.count if vw else 0

    def num_words(self) -> int:
        return len(self._by_index)

    def words(self) -> List[str]:
        return [w.word for w in self._by_index]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._by_index)

    def counts_array(self) -> np.ndarray:
        return np.array([w.count for w in self._by_index], dtype=np.int64)


class VocabConstructor:
    """Corpus scan → finalized VocabCache (parity: ``VocabConstructor``)."""

    def __init__(self, min_word_frequency: int = 1,
                 limit: Optional[int] = None):
        self.min_word_frequency = min_word_frequency
        self.limit = limit

    def build(self, token_sequences: Iterable[List[str]]) -> VocabCache:
        cache = VocabCache()
        for seq in token_sequences:
            for tok in seq:
                cache.add_token(tok)
        cache.finalize(self.min_word_frequency, self.limit)
        return cache


class Huffman:
    """Huffman tree over word frequencies → (codes, points) per word for
    hierarchical softmax (parity: ``Huffman.java``). ``points`` index the
    inner-node parameter table (size vocab-1)."""

    MAX_CODE_LENGTH = 40

    def __init__(self, vocab: VocabCache):
        self.vocab = vocab

    def apply(self) -> int:
        """Fill codes/points on every VocabWord. Returns max code length."""
        words = self.vocab.vocab_words()
        n = len(words)
        if n == 0:
            return 0
        if n == 1:
            words[0].codes, words[0].points = (0,), (0,)
            return 1
        # heap of (count, tie, node_id); leaves are 0..n-1, inner n..2n-2
        heap: List[Tuple[int, int, int]] = [
            (w.count, i, i) for i, w in enumerate(words)]
        heapq.heapify(heap)
        parent = {}
        binary = {}
        next_id = n
        while len(heap) > 1:
            c1, _, n1 = heapq.heappop(heap)
            c2, _, n2 = heapq.heappop(heap)
            parent[n1], parent[n2] = next_id, next_id
            binary[n1], binary[n2] = 0, 1
            heapq.heappush(heap, (c1 + c2, next_id, next_id))
            next_id += 1
        root = next_id - 1
        max_len = 0
        for i, w in enumerate(words):
            code, points = [], []
            node = i
            while node != root:
                code.append(binary[node])
                points.append(parent[node] - n)  # inner-node param index
                node = parent[node]
            code.reverse()
            points.reverse()
            w.codes = tuple(code)
            w.points = tuple(points)
            max_len = max(max_len, len(code))
        return max_len

    def padded_tables(self, max_len: Optional[int] = None
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(codes [V, L], points [V, L], lengths [V]) padded int arrays for
        the vectorized HS training step."""
        words = self.vocab.vocab_words()
        L = max_len or max((len(w.codes) for w in words), default=0)
        V = len(words)
        codes = np.zeros((V, L), dtype=np.int32)
        points = np.zeros((V, L), dtype=np.int32)
        lengths = np.zeros((V,), dtype=np.int32)
        for i, w in enumerate(words):
            l = min(len(w.codes), L)
            codes[i, :l] = w.codes[:l]
            points[i, :l] = w.points[:l]
            lengths[i] = l
        return codes, points, lengths
