"""Embedding learning algorithms: SkipGram / CBOW × negative-sampling / HS.

Parity: reference ``models/embeddings/learning/impl/elements/SkipGram.java:216``
(``iterateSample`` — per-word HS dot/gradient loop + negative sampling) and
``CBOW.java``.

TPU-native design: one jitted SGD step per index batch. ``jnp.take`` gathers
rows; differentiating the gather makes XLA emit scatter-adds — the vectorized
equivalent of the reference's per-word axpy updates, with the whole batch's
forward+backward fused into one XLA program. The unigram^0.75 negative table
and window/subsampling logic stay host-side (numpy) in sequence_vectors.py.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_params(vocab_size: int, dim: int, seed: int = 42,
                hs_nodes: int = 0, use_neg: bool = True,
                extra_vectors: int = 0) -> Dict[str, jnp.ndarray]:
    """syn0 ~ U(-0.5/dim, 0.5/dim) (word2vec convention); output tables zero.

    extra_vectors: additional rows in syn0 beyond the vocab (ParagraphVectors
    doc vectors live there).
    """
    rng = np.random.default_rng(seed)
    rows = vocab_size + extra_vectors
    params = {"syn0": jnp.asarray(
        (rng.random((rows, dim), dtype=np.float32) - 0.5) / dim)}
    if use_neg:
        params["syn1neg"] = jnp.zeros((vocab_size, dim), jnp.float32)
    if hs_nodes > 0:
        params["syn1"] = jnp.zeros((hs_nodes, dim), jnp.float32)
    return params


# ----------------------------------------------------------------------
# loss terms (shared by skip-gram and CBOW: they differ only in how the
# input vector v is formed)
# ----------------------------------------------------------------------


def _ns_loss(params, v, target, negs):
    """Negative-sampling loss for input vectors v [B,D] against target word
    ids [B] and negatives [B,K]."""
    u_pos = jnp.take(params["syn1neg"], target, axis=0)        # [B,D]
    u_neg = jnp.take(params["syn1neg"], negs, axis=0)          # [B,K,D]
    pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, axis=-1))
    neg = jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", v, u_neg))
    return -(jnp.sum(pos) + jnp.sum(neg))


def _hs_loss(params, v, codes, points, code_mask):
    """Hierarchical-softmax loss: codes/points [B,L] (padded), mask [B,L]."""
    u = jnp.take(params["syn1"], points, axis=0)               # [B,L,D]
    dots = jnp.einsum("bd,bld->bl", v, u)
    # code 0 → predict sigmoid→1, code 1 → 0 (word2vec convention)
    sign = 1.0 - 2.0 * codes.astype(v.dtype)
    logp = jax.nn.log_sigmoid(sign * dots) * code_mask
    return -jnp.sum(logp)


# ----------------------------------------------------------------------
# jitted steps
# ----------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("cbow",))
def ns_step(params, center, target, negs, ctx, ctx_mask, lr, *, cbow=False):
    """One SGD step, negative sampling.

    skip-gram: v = syn0[center];  cbow: v = masked mean of syn0[ctx].
    center/target [B], negs [B,K], ctx [B,W], ctx_mask [B,W].
    """
    def loss_fn(p):
        if cbow:
            vecs = jnp.take(p["syn0"], ctx, axis=0)            # [B,W,D]
            m = ctx_mask[..., None]
            v = jnp.sum(vecs * m, axis=1) / jnp.maximum(
                jnp.sum(m, axis=1), 1.0)
        else:
            v = jnp.take(p["syn0"], center, axis=0)
        # SUM (not mean): each pair takes a full lr-sized step, matching the
        # reference/word2vec per-sample SGD semantics (colliding rows
        # accumulate, the batched analog of sequential updates)
        return _ns_loss(p, v, target, negs)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss / center.shape[0]


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("cbow",))
def hs_step(params, center, codes, points, code_mask, ctx, ctx_mask, lr, *,
            cbow=False):
    """One SGD step, hierarchical softmax. codes/points/mask [B,L]."""
    def loss_fn(p):
        if cbow:
            vecs = jnp.take(p["syn0"], ctx, axis=0)
            m = ctx_mask[..., None]
            v = jnp.sum(vecs * m, axis=1) / jnp.maximum(
                jnp.sum(m, axis=1), 1.0)
        else:
            v = jnp.take(p["syn0"], center, axis=0)
        return _hs_loss(p, v, codes, points, code_mask)  # sum: see ns_step

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss / center.shape[0]


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("cbow",))
def ns_step_scan(params, centers, targets, negss, ctxs, ctx_masks, lr, *,
                 cbow=False):
    """K negative-sampling SGD steps fused into ONE dispatch via lax.scan.

    centers/targets [K,B], negss [K,B,N]; cbow adds ctxs/ctx_masks [K,B,W].
    The on-chip inner loop for high-throughput vocab training — same update
    semantics as calling :func:`ns_step` K times. Returns (params, [K] mean
    losses).
    """
    def one(p, batch):
        center, target, negs, ctx, ctx_mask = batch

        def loss_fn(p):
            if cbow:
                vecs = jnp.take(p["syn0"], ctx, axis=0)
                m = ctx_mask[..., None]
                v = jnp.sum(vecs * m, axis=1) / jnp.maximum(
                    jnp.sum(m, axis=1), 1.0)
            else:
                v = jnp.take(p["syn0"], center, axis=0)
            return _ns_loss(p, v, target, negs)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        p = jax.tree_util.tree_map(lambda a, g: a - lr * g, p, grads)
        return p, loss / center.shape[0]

    if ctxs is None:
        k, b = centers.shape
        ctxs = jnp.zeros((k, b, 1), jnp.int32)
        ctx_masks = jnp.zeros((k, b, 1), jnp.float32)
    return jax.lax.scan(one, params, (centers, targets, negss, ctxs,
                                      ctx_masks))


def make_sharded_ns_step(mesh, *, cbow: bool = False, axis: str = "data"):
    """Data-parallel negative-sampling step over a device mesh.

    Parity: the reference's distributed embedding training is Spark
    word2vec (``dl4j-spark-nlp/.../word2vec/Word2Vec.java`` — partitions
    train replicas, driver averages). TPU-native design: the PAIR BATCH is
    sharded over the mesh's ``axis``; params stay replicated, and because
    the loss is a sum over pairs XLA inserts the gradient all-reduce over
    ICI — per-step exact synchronization (strictly stronger than the
    reference's per-partition averaging), zero parameter shipping.

    Returns a jitted ``step(params, center, target, negs, ctx, ctx_mask,
    lr) -> (params, mean_loss)``; batch length must divide by the mesh
    axis size.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis))

    def step(params, center, target, negs, ctx, ctx_mask, lr):
        def loss_fn(p):
            if cbow:
                vecs = jnp.take(p["syn0"], ctx, axis=0)
                m = ctx_mask[..., None]
                v = jnp.sum(vecs * m, axis=1) / jnp.maximum(
                    jnp.sum(m, axis=1), 1.0)
            else:
                v = jnp.take(p["syn0"], center, axis=0)
            return _ns_loss(p, v, target, negs)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree_util.tree_map(lambda a, g: a - lr * g, params,
                                        grads)
        return params, loss / center.shape[0]

    return jax.jit(
        step, donate_argnums=(0,),
        in_shardings=(repl, shard, shard, shard, shard, shard, repl),
        out_shardings=(repl, repl))


def build_unigram_table(counts: np.ndarray, power: float = 0.75,
                        table_size: int = 1 << 20) -> np.ndarray:
    """word2vec's unigram^0.75 negative-sampling table (parity: the
    ``table`` in the reference's SkipGram negative sampling)."""
    probs = counts.astype(np.float64) ** power
    probs /= probs.sum()
    return np.searchsorted(np.cumsum(probs),
                           (np.arange(table_size) + 0.5) / table_size
                           ).astype(np.int32)
