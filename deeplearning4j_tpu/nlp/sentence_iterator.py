"""Sentence iterators (parity: reference ``text/sentenceiterator/`` —
``BasicLineIterator``, ``CollectionSentenceIterator``,
``FileSentenceIterator``, ``LineSentenceIterator`` + preprocessors)."""

from __future__ import annotations

import os
from typing import Callable, Iterable, Iterator, List, Optional


class SentenceIterator:
    """Streaming sentence source with reset semantics."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor

    def _raw(self) -> Iterator[str]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        for s in self._raw():
            s = s.strip()
            if not s:
                continue
            yield self.preprocessor(s) if self.preprocessor else s

    def reset(self) -> None:
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str], preprocessor=None):
        super().__init__(preprocessor)
        self.sentences = list(sentences)

    def _raw(self) -> Iterator[str]:
        return iter(self.sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a text file (parity: ``BasicLineIterator``)."""

    def __init__(self, path: str, preprocessor=None, encoding: str = "utf-8"):
        super().__init__(preprocessor)
        self.path = path
        self.encoding = encoding

    def _raw(self) -> Iterator[str]:
        with open(self.path, "r", encoding=self.encoding) as f:
            for line in f:
                yield line


class FileSentenceIterator(SentenceIterator):
    """Every file under a directory, one sentence per line (parity:
    ``FileSentenceIterator``)."""

    def __init__(self, directory: str, preprocessor=None,
                 encoding: str = "utf-8"):
        super().__init__(preprocessor)
        self.directory = directory
        self.encoding = encoding

    def _raw(self) -> Iterator[str]:
        for root, _, files in os.walk(self.directory):
            for name in sorted(files):
                with open(os.path.join(root, name), "r",
                          encoding=self.encoding, errors="replace") as f:
                    for line in f:
                        yield line
