"""Tokenization (parity: reference ``text/tokenization/`` —
``DefaultTokenizer``, ``NGramTokenizer``, ``tokenizerfactory/``,
``CommonPreprocessor``/``EndingPreProcessor``)."""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits-adjacent junk (parity:
    ``CommonPreprocessor.java``)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer dropping common English endings (parity:
    ``EndingPreProcessor.java``)."""

    def pre_process(self, token: str) -> str:
        for ending in ("sses", "ies", "ing", "ed", "s"):
            if token.endswith(ending) and len(token) > len(ending) + 2:
                return token[: -len(ending)]
        return token


class Tokenizer:
    def get_tokens(self) -> List[str]:
        raise NotImplementedError


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenizer with optional per-token preprocessing."""

    def __init__(self, text: str,
                 preprocessor: Optional[TokenPreProcess] = None):
        self.text = text
        self.preprocessor = preprocessor

    def get_tokens(self) -> List[str]:
        tokens = self.text.split()
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
        return [t for t in tokens if t]


class NGramTokenizer(Tokenizer):
    """Emits n-grams (joined by '_') over the base tokens (parity:
    ``NGramTokenizer.java``)."""

    def __init__(self, base: Tokenizer, min_n: int, max_n: int):
        self.base = base
        self.min_n, self.max_n = int(min_n), int(max_n)

    def get_tokens(self) -> List[str]:
        toks = self.base.get_tokens()
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            if n == 1:
                out.extend(toks)
            else:
                out.extend("_".join(toks[i:i + n])
                           for i in range(len(toks) - n + 1))
        return out


class TokenizerFactory:
    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self.preprocessor = pre


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self.preprocessor = preprocessor

    def create(self, text: str) -> Tokenizer:
        return DefaultTokenizer(text, self.preprocessor)


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, min_n: int = 1, max_n: int = 2,
                 preprocessor: Optional[TokenPreProcess] = None):
        self.min_n, self.max_n = min_n, max_n
        self.preprocessor = preprocessor

    def create(self, text: str) -> Tokenizer:
        return NGramTokenizer(DefaultTokenizer(text, self.preprocessor),
                              self.min_n, self.max_n)
