"""In-memory inverted index: word → posting list of document ids.

Parity: reference ``text/invertedindex/InvertedIndex.java:35`` — the
contract behind corpus sampling and doc retrieval (``document(index)``,
``documents(word)``, ``numDocuments()``, ``addWordsToDoc``, batch/sample
iteration). The reference's only in-tree impl was Lucene-backed; this is a
dependency-free postings map with the same surface.
"""

from __future__ import annotations

import bisect
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np


class InvertedIndex:
    """Postings over tokenized documents."""

    def __init__(self):
        self._docs: List[List[str]] = []
        self._postings: Dict[str, List[int]] = {}

    # ------------------------------------------------------------------
    # construction (parity: addWordsToDoc / addWordToDoc)
    # ------------------------------------------------------------------

    def add_words_to_doc(self, doc_id: Optional[int],
                         words: Sequence[str]) -> int:
        """Append a document (or extend an existing id); returns the doc id."""
        if doc_id is None or doc_id >= len(self._docs):
            doc_id = len(self._docs)
            self._docs.append([])
        doc = self._docs[doc_id]
        for w in words:
            doc.append(w)
            plist = self._postings.setdefault(w, [])
            # keep postings sorted + unique even when an earlier doc is
            # re-extended after newer docs exist (code review r4)
            i = bisect.bisect_left(plist, doc_id)
            if i >= len(plist) or plist[i] != doc_id:
                plist.insert(i, doc_id)
        return doc_id

    def add_word_to_doc(self, doc_id: int, word: str) -> None:
        self.add_words_to_doc(doc_id if doc_id < len(self._docs) else None,
                              [word])

    # ------------------------------------------------------------------
    # retrieval (parity: document / documents / numDocuments / allDocs)
    # ------------------------------------------------------------------

    def document(self, index: int) -> List[str]:
        return list(self._docs[index])

    def documents(self, word: str) -> List[int]:
        """Posting list: ids of documents containing the word."""
        return list(self._postings.get(word, ()))

    def num_documents(self) -> int:
        return len(self._docs)

    def num_documents_containing(self, word: str) -> int:
        return len(self._postings.get(word, ()))

    def all_docs(self) -> Iterator[List[str]]:
        for d in self._docs:
            yield list(d)

    def total_words(self) -> int:
        return sum(len(d) for d in self._docs)

    # ------------------------------------------------------------------
    # sampling (parity: the batch/sample methods backing corpus iteration)
    # ------------------------------------------------------------------

    def sample_docs(self, n: int, seed: Optional[int] = None) -> List[int]:
        """n document ids sampled without replacement (or all, if fewer)."""
        rng = np.random.default_rng(seed)
        total = len(self._docs)
        if n >= total:
            return list(range(total))
        return list(rng.choice(total, size=n, replace=False))

    def batches(self, batch_size: int) -> Iterator[List[List[str]]]:
        """Documents in fixed-size batches (last may be short)."""
        for i in range(0, len(self._docs), batch_size):
            yield [list(d) for d in self._docs[i:i + batch_size]]

    def eachdoc(self, fn) -> None:
        """Apply fn(tokens, doc_id) to every document (parity: eachDoc)."""
        for i, d in enumerate(self._docs):
            fn(list(d), i)
