"""Word2Vec: the user-facing builder over SequenceVectors + serde.

Parity: reference ``models/word2vec/Word2Vec.java`` (builder:
``layerSize/windowSize/minWordFrequency/negativeSample/iterations/epochs/
sampling/learningRate/minLearningRate/seed/iterate/tokenizerFactory``) and
``loader/WordVectorSerializer.java`` (word2vec text format read/write).
"""

from __future__ import annotations

import gzip
from typing import Iterable, List, Optional

import numpy as np

from .sentence_iterator import SentenceIterator
from .sequence_vectors import SequenceVectors
from .tokenization import DefaultTokenizerFactory, TokenizerFactory
from .vocab import VocabCache


class Word2Vec(SequenceVectors):
    """Word embeddings from a sentence source.

    Usage (mirrors the reference builder)::

        w2v = (Word2Vec.builder()
               .layer_size(100).window_size(5).min_word_frequency(5)
               .iterate(sentence_iterator)
               .tokenizer_factory(DefaultTokenizerFactory())
               .build())
        w2v.fit()
        w2v.words_nearest("day")
    """

    def __init__(self, sentence_iterator=None,
                 tokenizer_factory: Optional[TokenizerFactory] = None, **kw):
        super().__init__(**kw)
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()

    # -- builder (fluent, reference-style) --
    class Builder:
        def __init__(self):
            self._kw = {}
            self._iter = None
            self._tok = None

        def layer_size(self, n): self._kw["layer_size"] = int(n); return self
        def window_size(self, n): self._kw["window"] = int(n); return self
        def min_word_frequency(self, n): self._kw["min_word_frequency"] = int(n); return self
        def negative_sample(self, n): self._kw["negative"] = int(n); return self
        def sampling(self, s): self._kw["sample"] = float(s); return self
        def learning_rate(self, lr): self._kw["learning_rate"] = float(lr); return self
        def min_learning_rate(self, lr): self._kw["min_learning_rate"] = float(lr); return self
        def epochs(self, n): self._kw["epochs"] = int(n); return self
        def iterations(self, n): return self.epochs(n)
        def batch_size(self, n): self._kw["batch_size"] = int(n); return self
        def seed(self, s): self._kw["seed"] = int(s); return self
        def use_cbow(self, flag=True): self._kw["use_cbow"] = bool(flag); return self
        def limit_vocabulary_size(self, n): self._kw["vocab_limit"] = int(n); return self

        def iterate(self, sentence_iterator): self._iter = sentence_iterator; return self
        def tokenizer_factory(self, tf): self._tok = tf; return self

        def build(self) -> "Word2Vec":
            return Word2Vec(self._iter, self._tok, **self._kw)

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    # -- fit from the configured sentence source --
    def _token_sequences(self) -> List[List[str]]:
        if self.sentence_iterator is None:
            raise ValueError("no sentence iterator configured (builder.iterate)")
        return [self.tokenizer_factory.create(s).get_tokens()
                for s in self.sentence_iterator]

    def fit(self, sequences=None, resettable: bool = True) -> "Word2Vec":
        if sequences is None:
            sequences = self._token_sequences()
        else:
            # Raw sentence strings go through the tokenizer factory, same as
            # the configured sentence source (ref SentenceTransformer.java).
            sequences = [self.tokenizer_factory.create(s).get_tokens()
                         if isinstance(s, str) else s
                         for s in sequences]
        super().fit(sequences, resettable)
        return self


class WordVectorSerializer:
    """word2vec text-format read/write (parity:
    ``WordVectorSerializer.writeWordVectors/loadTxtVectors``)."""

    @staticmethod
    def write_word_vectors(model: SequenceVectors, path: str) -> None:
        opener = gzip.open if path.endswith(".gz") else open
        syn0 = model._syn0()
        with opener(path, "wt", encoding="utf-8") as f:
            f.write(f"{model.vocab.num_words()} {model.layer_size}\n")
            for i, word in enumerate(model.vocab.words()):
                vec = " ".join(f"{v:.6f}" for v in syn0[i])
                f.write(f"{word} {vec}\n")

    @staticmethod
    def write_word_vectors_binary(model: SequenceVectors, path: str) -> None:
        """Google word2vec C binary format write (parity:
        ``WordVectorSerializer.writeWordVectors`` binary branch): ASCII
        header ``"<n_words> <dim>\\n"``, then per word the UTF-8 word bytes,
        a space, ``dim`` little-endian float32s, and a newline — the layout
        the original word2vec C tool emits and the ecosystem interchanges."""
        opener = gzip.open if path.endswith(".gz") else open
        syn0 = np.asarray(model._syn0(), dtype="<f4")
        # the format's only word terminator is a single space, so any
        # whitespace inside a token desynchronizes every reader (ours and
        # the ecosystem's) from the first such word on — refuse at write
        # time instead of emitting a corrupt file
        for word in model.vocab.words():
            if word != word.strip() or any(ch.isspace() for ch in word):
                raise ValueError(
                    f"vocab word {word!r} contains whitespace — the "
                    "word2vec C binary format cannot represent it; clean "
                    "the tokenization before writing binary vectors")
        with opener(path, "wb") as f:
            f.write(f"{model.vocab.num_words()} {model.layer_size}\n"
                    .encode("utf-8"))
            for i, word in enumerate(model.vocab.words()):
                f.write(word.encode("utf-8") + b" ")
                f.write(syn0[i].tobytes())
                f.write(b"\n")

    @staticmethod
    def load_google_model(path: str, binary: bool = True) -> SequenceVectors:
        """Load a Google-format word2vec model (parity:
        ``WordVectorSerializer.java:109-152`` ``loadGoogleModel``): binary
        (word2vec C ``fwrite`` float32 layout) or text."""
        if not binary:
            return WordVectorSerializer.load_txt_vectors(path)
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            header = f.readline().decode("utf-8").split()
            n_words, dim = int(header[0]), int(header[1])
            vec_bytes = dim * 4
            words, vecs = [], []
            for _ in range(n_words):
                # word bytes run to the separating space (skip leading
                # newlines some writers leave after the previous vector)
                chars = []
                while True:
                    ch = f.read(1)
                    if not ch:
                        raise ValueError(
                            f"truncated binary model: read {len(words)} of "
                            f"{n_words} words")
                    if ch == b" ":
                        break
                    if ch != b"\n":
                        chars.append(ch)
                words.append(b"".join(chars).decode("utf-8"))
                buf = f.read(vec_bytes)
                if len(buf) != vec_bytes:
                    raise ValueError(
                        f"truncated vector for word {words[-1]!r}")
                vecs.append(np.frombuffer(buf, dtype="<f4").copy())
        return WordVectorSerializer._from_words_vecs(words, vecs, dim)

    @staticmethod
    def _from_words_vecs(words, vecs, dim) -> SequenceVectors:
        model = SequenceVectors(layer_size=dim)
        vocab = VocabCache()
        for w in words:
            vocab.add_token(w)
        vocab.finalize()
        # finalize() sorts by (count desc, word) — re-map to file order
        order = [vocab.index_of(w) for w in words]
        syn0 = np.zeros((len(words), dim), dtype=np.float32)
        for src, dst in enumerate(order):
            syn0[dst] = vecs[src]
        import jax.numpy as jnp
        model.vocab = vocab
        model.params = {"syn0": jnp.asarray(syn0)}
        return model

    @staticmethod
    def load_txt_vectors(path: str) -> SequenceVectors:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as f:
            header = f.readline().split()
            n_words, dim = int(header[0]), int(header[1])
            words, vecs = [], []
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < dim + 1:
                    continue
                words.append(parts[0])
                vecs.append(np.asarray(parts[1:dim + 1], dtype=np.float32))
        return WordVectorSerializer._from_words_vecs(words, vecs, dim)
