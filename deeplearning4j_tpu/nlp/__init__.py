"""NLP / embeddings stack.

Parity: reference ``deeplearning4j-nlp-parent`` (~34k LoC) —
``SequenceVectors.java:161`` (the generic embedding trainer),
``SkipGram.java:216`` / ``CBOW.java`` (learning algorithms), ``Word2Vec``,
``ParagraphVectors`` (``inferVector``), ``Glove``, vocab
(``AbstractCache``, ``VocabConstructor``), Huffman tree, tokenization +
sentence iterators, and ``WordVectorSerializer`` formats.

TPU-native design (NOT a port): the reference trains embeddings with
lock-free multithreaded per-word gemv updates (Hogwild,
``SequenceVectors.java:245-260``). Here the host side only *prepares index
batches* — (center, context/code-path, negatives) int arrays — and ONE jitted
step per batch does the whole update vectorized: ``jnp.take`` gathers,
fused sigmoid-dot losses, ``jax.grad``, and ``segment_sum`` scatter-adds.
Negative sampling and hierarchical softmax are both expressed this way; the
random-window/subsampling logic runs in numpy on host.
"""

from .documents import (
    AsyncLabelAwareIterator, BasicLabelAwareIterator, FileDocumentIterator,
    FileLabelAwareIterator, FilenamesLabelAwareIterator, LabelAwareIterator,
    LabelledDocument, LabelsSource, SimpleLabelAwareIterator)
from .glove import Glove
from .inverted_index import InvertedIndex
from .paragraph_vectors import ParagraphVectors
from .sentence_iterator import (
    BasicLineIterator, CollectionSentenceIterator, FileSentenceIterator,
    SentenceIterator)
from .sequence_vectors import SequenceVectors
from .tokenization import (
    DefaultTokenizer, DefaultTokenizerFactory, NGramTokenizerFactory,
    CommonPreprocessor)
from .vectorizers import BagOfWordsVectorizer, TextVectorizer, TfidfVectorizer
from .vocab import Huffman, VocabCache, VocabWord
from .word2vec import Word2Vec, WordVectorSerializer

__all__ = [
    "Word2Vec", "ParagraphVectors", "Glove", "SequenceVectors",
    "VocabCache", "VocabWord", "Huffman",
    "DefaultTokenizer", "DefaultTokenizerFactory", "NGramTokenizerFactory",
    "CommonPreprocessor",
    "SentenceIterator", "BasicLineIterator", "CollectionSentenceIterator",
    "FileSentenceIterator",
    "WordVectorSerializer",
    "LabelledDocument", "LabelsSource", "LabelAwareIterator",
    "SimpleLabelAwareIterator", "BasicLabelAwareIterator",
    "FileLabelAwareIterator", "FilenamesLabelAwareIterator",
    "AsyncLabelAwareIterator", "FileDocumentIterator",
    "BagOfWordsVectorizer", "TfidfVectorizer", "TextVectorizer",
    "InvertedIndex",
]
