"""Label-aware document iteration (the doc2vec / supervised-text ETL seam).

Parity: reference ``text/documentiterator/`` — ``LabelledDocument``,
``LabelsSource`` (auto-generated or declared label sets,
``LabelsSource.java:16-117``), ``LabelAwareIterator`` and its
implementations (``BasicLabelAwareIterator``, ``SimpleLabelAwareIterator``,
``FileLabelAwareIterator``, ``FilenamesLabelAwareIterator``,
``AsyncLabelAwareIterator``) plus the plain ``FileDocumentIterator``.

Host-side ETL: pure Python/queue code (the TPU never sees strings); feeds
ParagraphVectors and the vectorizers (:mod:`.vectorizers`).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from pathlib import Path
from typing import Iterable, Iterator, List, Optional


@dataclasses.dataclass
class LabelledDocument:
    """One document with its label(s) (parity: ``LabelledDocument.java``)."""

    content: str
    labels: List[str] = dataclasses.field(default_factory=list)

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelsSource:
    """Label bookkeeping: declared list or generated from a template
    (parity: ``LabelsSource.java`` — ``%d`` template → DOC_0, DOC_1, ...)."""

    def __init__(self, labels: Optional[List[str]] = None,
                 template: Optional[str] = None):
        self.template = template
        self._labels: List[str] = list(labels) if labels else []
        self._index = {l: i for i, l in enumerate(self._labels)}
        self._counter = 0

    def next_label(self) -> str:
        if self.template is None:
            raise ValueError("next_label() needs a template LabelsSource")
        label = (self.template % self._counter if "%" in self.template
                 else f"{self.template}{self._counter}")
        self._counter += 1
        self.store_label(label)
        return label

    def store_label(self, label: str) -> None:
        if label not in self._index:
            self._index[label] = len(self._labels)
            self._labels.append(label)

    def index_of(self, label: str) -> int:
        return self._index.get(label, -1)

    @property
    def labels(self) -> List[str]:
        return list(self._labels)

    def size(self) -> int:
        return len(self._labels)

    def reset(self) -> None:
        self._counter = 0


class LabelAwareIterator:
    """Iterator of :class:`LabelledDocument` (parity:
    ``LabelAwareIterator.java``)."""

    labels_source: LabelsSource

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_document(self) -> LabelledDocument:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[LabelledDocument]:
        self.reset()
        while self.has_next():
            yield self.next_document()


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Over an in-memory collection of LabelledDocuments (parity:
    ``SimpleLabelAwareIterator.java``)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)
        self.labels_source = LabelsSource()
        for d in self._docs:
            for l in d.labels:
                self.labels_source.store_label(l)
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._docs)

    def next_document(self) -> LabelledDocument:
        if not self.has_next():
            raise StopIteration
        d = self._docs[self._cursor]
        self._cursor += 1
        return d

    def reset(self) -> None:
        self._cursor = 0


class BasicLabelAwareIterator(LabelAwareIterator):
    """Wraps a sentence iterator, assigning generated labels (parity:
    ``BasicLabelAwareIterator.java`` — the doc2vec default where every
    sentence is a document labelled DOC_n)."""

    def __init__(self, sentences: Iterable[str],
                 label_template: str = "DOC_%d"):
        self._sentences = sentences
        self.labels_source = LabelsSource(template=label_template)
        self._iter: Optional[Iterator[str]] = None
        self._peek: Optional[str] = None

    def _ensure(self) -> None:
        if self._iter is None:
            if hasattr(self._sentences, "reset"):
                self._sentences.reset()
            self._iter = iter(self._sentences)

    def has_next(self) -> bool:
        self._ensure()
        if self._peek is None:
            self._peek = next(self._iter, None)
        return self._peek is not None

    def next_document(self) -> LabelledDocument:
        if not self.has_next():
            raise StopIteration
        content, self._peek = self._peek, None
        return LabelledDocument(content=content,
                                labels=[self.labels_source.next_label()])

    def reset(self) -> None:
        self._iter = None
        self._peek = None
        self.labels_source.reset()


class FileLabelAwareIterator(LabelAwareIterator):
    """Directory layout ``root/<label>/<file>`` → one document per file,
    labelled by its parent dir (parity: ``FileLabelAwareIterator.java``)."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        self.root = Path(root)
        self.encoding = encoding
        self._files: List[Path] = sorted(
            p for p in self.root.glob("*/*") if p.is_file())
        self.labels_source = LabelsSource()
        for p in self._files:
            self.labels_source.store_label(p.parent.name)
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._files)

    def next_document(self) -> LabelledDocument:
        if not self.has_next():
            raise StopIteration
        p = self._files[self._cursor]
        self._cursor += 1
        return LabelledDocument(content=p.read_text(self.encoding),
                                labels=[p.parent.name])

    def reset(self) -> None:
        self._cursor = 0


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """One document per file, labelled by the FILENAME (parity:
    ``FilenamesLabelAwareIterator.java``)."""

    def __init__(self, files: Iterable[str], encoding: str = "utf-8",
                 absolute_labels: bool = False):
        self._files = [Path(f) for f in files]
        self.encoding = encoding
        self.absolute_labels = absolute_labels
        self.labels_source = LabelsSource()
        for p in self._files:
            self.labels_source.store_label(self._label_of(p))
        self._cursor = 0

    def _label_of(self, p: Path) -> str:
        return str(p) if self.absolute_labels else p.name

    def has_next(self) -> bool:
        return self._cursor < len(self._files)

    def next_document(self) -> LabelledDocument:
        if not self.has_next():
            raise StopIteration
        p = self._files[self._cursor]
        self._cursor += 1
        return LabelledDocument(content=p.read_text(self.encoding),
                                labels=[self._label_of(p)])

    def reset(self) -> None:
        self._cursor = 0


class AsyncLabelAwareIterator(LabelAwareIterator):
    """Background-thread prefetch over any LabelAwareIterator (parity:
    ``AsyncLabelAwareIterator.java`` — same producer/queue design as
    AsyncDataSetIterator)."""

    _SENTINEL = object()

    def __init__(self, base: LabelAwareIterator, buffer_size: int = 64):
        self.base = base
        self.labels_source = base.labels_source
        self.buffer_size = max(1, int(buffer_size))
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        self._error: Optional[BaseException] = None
        self._peek = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._start()

    def _producer(self, stop: threading.Event, q: "queue.Queue") -> None:
        try:
            while not stop.is_set() and self.base.has_next():
                doc = self.base.next_document()
                while True:
                    try:
                        q.put(doc, timeout=0.1)
                        break
                    except queue.Full:
                        if stop.is_set():
                            return
        except BaseException as e:
            self._error = e
        finally:
            if stop.is_set():
                # reset() already drained and abandoned this queue
                try:
                    q.put_nowait(self._SENTINEL)
                except queue.Full:
                    pass
            else:
                q.put(self._SENTINEL)

    def _start(self) -> None:
        self._thread = threading.Thread(
            target=self._producer, args=(self._stop, self._queue), daemon=True)
        self._thread.start()

    def has_next(self) -> bool:
        if self._peek is None:
            self._peek = self._queue.get()
        if self._peek is self._SENTINEL:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return False
        return True

    def next_document(self) -> LabelledDocument:
        if not self.has_next():
            raise StopIteration
        out, self._peek = self._peek, None
        return out

    def reset(self) -> None:
        # signal the producer to stop (no full-corpus drain — code review r4),
        # unblock it, and restart on a reset base with a fresh queue
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                # a stale producer still touching the shared base iterator
                # would race the restarted one — refuse to double-consume
                raise RuntimeError(
                    "async producer did not stop within 5s; cannot safely "
                    "reset while it may still consume the base iterator")
        self._peek = None
        self._error = None
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self.buffer_size)
        self.base.reset()
        self._start()


class FileDocumentIterator:
    """Plain (label-free) document iterator over files in a directory
    (parity: ``FileDocumentIterator.java``)."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        self.root = Path(root)
        self.encoding = encoding
        self._files = sorted(p for p in self.root.rglob("*") if p.is_file())
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._files)

    def next_document(self) -> str:
        if not self.has_next():
            raise StopIteration
        p = self._files[self._cursor]
        self._cursor += 1
        return p.read_text(self.encoding)

    def reset(self) -> None:
        self._cursor = 0

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_document()
