"""GloVe: global-vector embeddings from co-occurrence statistics.

Parity: reference ``models/glove/Glove.java`` (+ ``glove/count/`` co-occurrence
counting): weighted least squares  f(X_ij)(w_i·w̃_j + b_i + b̃_j − log X_ij)²
with AdaGrad per-parameter learning rates.

TPU-native: co-occurrence counting is a host-side dict sweep; training is a
jitted AdaGrad step over shuffled (i, j, X_ij) triples — gathers + grads →
scatter-add, like the word2vec steps.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from .sequence_vectors import SequenceVectors
from .vocab import VocabConstructor


@functools.partial(__import__("jax").jit, donate_argnums=(0, 1))
def _glove_step(params, accum, rows, cols, logx, weight, lr):
    import jax
    import jax.numpy as jnp

    def loss_fn(p):
        wi = jnp.take(p["w"], rows, axis=0)
        wj = jnp.take(p["w_tilde"], cols, axis=0)
        bi = jnp.take(p["b"], rows)
        bj = jnp.take(p["b_tilde"], cols)
        diff = jnp.sum(wi * wj, axis=1) + bi + bj - logx
        return 0.5 * jnp.sum(weight * diff * diff) / rows.shape[0]

    loss, grads = jax.value_and_grad(loss_fn)(params)
    # AdaGrad: accumulate squared grads, scale updates
    accum = jax.tree_util.tree_map(lambda a, g: a + g * g, accum, grads)
    params = jax.tree_util.tree_map(
        lambda p, g, a: p - lr * g / jnp.sqrt(a + 1e-12), params, grads, accum)
    return params, accum, loss


class Glove(SequenceVectors):
    """GloVe trainer (reference builder knobs: ``xMax``, ``alpha``,
    ``symmetric``, ``shuffle``, ``learningRate``, ``epochs``)."""

    def __init__(self, *, x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, shuffle: bool = True, **kw):
        kw.setdefault("learning_rate", 0.05)
        super().__init__(**kw)
        self.x_max = float(x_max)
        self.alpha = float(alpha)
        self.symmetric = symmetric
        self.shuffle = shuffle
        self._accum = None

    def _count_cooccurrences(self, seqs: Iterable[List[int]]
                             ) -> Dict[Tuple[int, int], float]:
        counts: Dict[Tuple[int, int], float] = {}
        W = self.window
        for idx in seqs:
            n = len(idx)
            for pos in range(n):
                for off in range(1, W + 1):
                    j = pos + off
                    if j >= n:
                        break
                    a, b = int(idx[pos]), int(idx[j])
                    inc = 1.0 / off  # distance weighting (GloVe convention)
                    counts[(a, b)] = counts.get((a, b), 0.0) + inc
                    if self.symmetric:
                        counts[(b, a)] = counts.get((b, a), 0.0) + inc
        return counts

    def fit(self, sequences: Iterable[List[str]],
            resettable: bool = True) -> "Glove":
        import jax.numpy as jnp

        seqs = list(sequences)
        if self.vocab is None:
            self.build_vocab(seqs)
        indexed = []
        for seq in seqs:
            idx = [self.vocab.index_of(t) for t in seq]
            indexed.append([i for i in idx if i >= 0])
        counts = self._count_cooccurrences(indexed)
        if not counts:
            raise ValueError("empty co-occurrence matrix")
        pairs = np.array(list(counts.keys()), dtype=np.int32)
        xs = np.array(list(counts.values()), dtype=np.float32)
        logx = np.log(xs)
        weight = np.minimum((xs / self.x_max) ** self.alpha, 1.0).astype(np.float32)

        V, D = self.vocab.num_words(), self.layer_size
        rng = np.random.default_rng(self.seed)
        init = lambda shape: jnp.asarray(
            (rng.random(shape, dtype=np.float32) - 0.5) / D)
        self.params = {"w": init((V, D)), "w_tilde": init((V, D)),
                       "b": jnp.zeros(V, jnp.float32),
                       "b_tilde": jnp.zeros(V, jnp.float32)}
        self._accum = __import__("jax").tree_util.tree_map(
            jnp.zeros_like, self.params)

        B = self.batch_size
        order = np.arange(len(pairs))
        for _ in range(self.epochs):
            if self.shuffle:
                rng.shuffle(order)
            for s in range(0, len(order), B):
                sel = order[s:s + B]
                self.params, self._accum, _ = _glove_step(
                    self.params, self._accum,
                    jnp.asarray(pairs[sel, 0]), jnp.asarray(pairs[sel, 1]),
                    jnp.asarray(logx[sel]), jnp.asarray(weight[sel]),
                    jnp.float32(self.learning_rate))
        self._syn0_normed = None
        return self

    def _syn0(self) -> np.ndarray:
        # GloVe convention: final embedding = w + w̃
        return np.asarray(self.params["w"]) + np.asarray(self.params["w_tilde"])
