"""Evaluation: classification / regression / ROC metrics.

Parity: reference ``deeplearning4j-nn/src/main/java/org/deeplearning4j/eval/``
— ``Evaluation.java:410`` (``stats()``), ``:483/:531/:703``
(precision/recall/f1), ``ConfusionMatrix.java``, ``RegressionEvaluation.java``,
``ROC.java``.

TPU-native design: metric *accumulation* happens on host in numpy (cheap,
O(batch) counters); the expensive part — the forward pass producing the
predictions — stays a compiled XLA program on device. This mirrors how the
reference streams ``Evaluation.eval(labels, out)`` per minibatch but replaces
INDArray bookkeeping with numpy.
"""

from .confusion import ConfusionMatrix
from .evaluation import Evaluation, Prediction
from .regression import RegressionEvaluation
from .roc import ROC, ROCMultiClass

__all__ = [
    "ConfusionMatrix",
    "Evaluation",
    "Prediction",
    "RegressionEvaluation",
    "ROC",
    "ROCMultiClass",
]
