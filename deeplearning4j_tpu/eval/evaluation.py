"""Classification evaluation (parity: reference ``eval/Evaluation.java``).

Accumulates a confusion matrix from streamed minibatches and derives
accuracy / per-class precision / recall / F1 plus macro averages, matching
``Evaluation.java:410`` (``stats()``), ``:483`` (``precision``), ``:531``
(``recall``), ``:703`` (``f1``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .confusion import ConfusionMatrix


@dataclasses.dataclass(frozen=True)
class Prediction:
    """One example's (actual, predicted, provenance) triple (parity:
    reference ``eval/meta/Prediction.java``). ``record_metadata`` is
    whatever the iterator collected — normally a
    ``datavec.readers.RecordMetaData`` — so a misclassified example can be
    traced back to its source record and reloaded via
    ``RecordReaderDataSetIterator.load_from_metadata``."""

    actual_class: int
    predicted_class: int
    record_metadata: Any

    def location(self) -> str:
        meta = self.record_metadata
        return meta.location() if hasattr(meta, "location") else str(meta)


def _to_class_indices(arr: np.ndarray) -> np.ndarray:
    """Labels/predictions may be one-hot/probabilities [b, c] (or [b, c, t]
    time series) or already class indices [b]."""
    arr = np.asarray(arr)
    if arr.ndim == 1:
        return arr.astype(np.int64)
    return np.argmax(arr, axis=-1).reshape(-1)


class Evaluation:
    """Streaming classification metrics.

    Usage::

        ev = Evaluation()
        for x, y in batches:
            ev.eval(y, net.output(x))
        print(ev.stats())
    """

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[Sequence[str]] = None):
        self.num_classes = num_classes
        self.label_names = list(labels) if labels is not None else None
        self.confusion: Optional[ConfusionMatrix] = None
        self._examples = 0
        self._predictions: List[Prediction] = []

    # -- accumulation ---------------------------------------------------

    def _ensure_confusion(self, n: int) -> None:
        if self.confusion is None:
            size = self.num_classes or n
            self.confusion = ConfusionMatrix(range(size))
            self.num_classes = size

    def eval(self, labels, predictions, mask=None, metadata=None) -> None:
        """Accumulate one minibatch.

        labels: one-hot [b, c] (or [b, t, c] time series) or ints [b];
        predictions: probabilities, same leading shape; mask: optional
        per-row [b] / per-timestep [b, t] 0/1 array — masked rows are
        excluded (parity: ``Evaluation.evalTimeSeries`` masking).

        metadata: optional per-example provenance, one entry per row
        (parity: ``Evaluation.java:195`` ``eval(labels, out, metadata)``).
        When given, every example's (actual, predicted, metadata) triple is
        retained so ``get_prediction_errors()`` can answer *which source
        records* were misclassified. Per-example metadata attribution is a
        row-wise concept, so it requires per-example labels ([b, c] or
        [b]), not flattened time series.
        """
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim > 1:
            n_out = labels.shape[-1]
        else:
            # integer class indices: size from labels, plus predictions only
            # when those are indices too (not a probability matrix)
            n_out = int(labels.max(initial=0)) + 1
            if predictions.ndim == 1:
                n_out = max(n_out, int(predictions.max(initial=0)) + 1)
            else:
                n_out = max(n_out, predictions.shape[-1])
        self._ensure_confusion(n_out)
        if n_out > len(self.confusion.classes):
            # a later batch revealed new classes (int-label streams)
            self.confusion.grow_to(n_out)
            self.num_classes = n_out

        if labels.ndim == 3:  # [b, t, c] time series → flatten active steps
            if metadata is not None:
                raise ValueError(
                    "metadata attribution needs per-example labels "
                    "([b, c] or [b]); flatten time series yourself or "
                    "evaluate without metadata")
            b, t, c = labels.shape
            labels2 = labels.reshape(b * t, c)
            preds2 = predictions.reshape(b * t, c)
            if mask is not None:
                keep = np.asarray(mask).reshape(b * t) > 0
                labels2, preds2 = labels2[keep], preds2[keep]
            y_true = _to_class_indices(labels2)
            y_pred = _to_class_indices(preds2)
        else:
            y_true = _to_class_indices(labels)
            y_pred = _to_class_indices(predictions)
            metas = list(metadata) if metadata is not None else None
            if metas is not None and len(metas) != len(y_true):
                raise ValueError(
                    f"metadata has {len(metas)} entries for "
                    f"{len(y_true)} examples")
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                y_true, y_pred = y_true[keep], y_pred[keep]
                if metas is not None:
                    metas = [m for m, k in zip(metas, keep) if k]
            if metas is not None:
                self._predictions.extend(
                    Prediction(int(a), int(p), m)
                    for a, p, m in zip(y_true, y_pred, metas))

        self.confusion.add_batch(y_true, y_pred)
        self._examples += len(y_true)

    def merge(self, other: "Evaluation") -> None:
        """Combine evaluations from parallel workers (parity: the Spark
        ``EvaluationReduceFunction``)."""
        if other.confusion is None:
            return
        if self.confusion is None:
            self.confusion = ConfusionMatrix(other.confusion.classes)
            self.num_classes = other.num_classes
        self.confusion.merge(other.confusion)
        self._examples += other._examples
        self._predictions.extend(other._predictions)

    # -- per-example metadata attribution -------------------------------
    # parity: reference eval/meta/Prediction.java + Evaluation.java:1013
    # (getPredictionErrors) / :1044 (getPredictionsByActualClass) /
    # :1075 (getPredictionByPredictedClass)

    def get_prediction_errors(self) -> List[Prediction]:
        """All misclassified examples seen with metadata, in eval order —
        answers "WHICH source records did the model get wrong"."""
        return [p for p in self._predictions
                if p.actual_class != p.predicted_class]

    def get_predictions_by_actual_class(self, cls: int) -> List[Prediction]:
        return [p for p in self._predictions if p.actual_class == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> List[Prediction]:
        return [p for p in self._predictions if p.predicted_class == cls]

    # -- per-class counts ----------------------------------------------

    def true_positives(self, cls: int) -> int:
        return self.confusion.count(cls, cls)

    def false_positives(self, cls: int) -> int:
        return self.confusion.predicted_total(cls) - self.true_positives(cls)

    def false_negatives(self, cls: int) -> int:
        return self.confusion.actual_total(cls) - self.true_positives(cls)

    def true_negatives(self, cls: int) -> int:
        return (self.confusion.total() - self.true_positives(cls)
                - self.false_positives(cls) - self.false_negatives(cls))

    # -- metrics --------------------------------------------------------

    def accuracy(self) -> float:
        total = self.confusion.total()
        if total == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / total

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.predicted_total(cls)
            return self.true_positives(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in self._seen_classes()]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.actual_total(cls)
            return self.true_positives(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in self._seen_classes()]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        vals = [self.f1(c) for c in self._seen_classes()]
        return float(np.mean(vals)) if vals else 0.0

    def _seen_classes(self) -> List[int]:
        """Classes that actually appear (as truth or prediction) — macro
        averages over absent classes would deflate scores, matching the
        reference's treatment of classes with no examples."""
        if self.confusion is None:
            return []
        seen = (self.confusion.matrix.sum(axis=0)
                + self.confusion.matrix.sum(axis=1)) > 0
        return [c for c, s in zip(self.confusion.classes, seen) if s]

    def num_examples(self) -> int:
        return self._examples

    def _label(self, c: int) -> str:
        if self.label_names and c < len(self.label_names):
            return self.label_names[c]
        return str(c)

    def stats(self) -> str:
        """Human-readable report (parity: ``Evaluation.stats()`` :410)."""
        if self.confusion is None:
            return "Evaluation: no data"
        lines = ["========================Evaluation========================="]
        lines.append(f" Examples:  {self._examples}")
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        lines.append("-----------------------------------------------------------")
        lines.append(" Per class:  class  precision  recall  f1  support")
        for c in self._seen_classes():
            lines.append(
                f"   {self._label(c):>8}  {self.precision(c):.4f}  "
                f"{self.recall(c):.4f}  {self.f1(c):.4f}  "
                f"{self.confusion.actual_total(c)}")
        lines.append("-----------------------------------------------------------")
        lines.append("Confusion matrix (rows=actual, cols=predicted):")
        lines.append(str(self.confusion))
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.stats()
