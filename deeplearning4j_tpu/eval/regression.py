"""Regression evaluation (parity: reference ``eval/RegressionEvaluation.java``).

Per-column MSE / MAE / RMSE / RSE / R² (correlation²) accumulated in a
streaming, numerically-stable way (sum / sum-of-squares / cross moments), so
it can be merged across data-parallel workers exactly.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None,
                 column_names: Optional[Sequence[str]] = None):
        self.n_columns = n_columns
        self.column_names = list(column_names) if column_names else None
        self._initialized = False

    def _init_accum(self, n: int) -> None:
        self.n_columns = n
        z = lambda: np.zeros(n, dtype=np.float64)
        self._count = z()
        self._sum_abs_err = z()
        self._sum_sq_err = z()
        self._sum_label = z()
        self._sum_pred = z()
        self._sum_label_sq = z()
        self._sum_pred_sq = z()
        self._sum_label_pred = z()
        self._initialized = True

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        if labels.ndim == 3:  # [b, t, c] → flatten time
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
        elif mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
        if not self._initialized:
            self._init_accum(labels.shape[1])
        err = predictions - labels
        self._count += labels.shape[0]
        self._sum_abs_err += np.abs(err).sum(axis=0)
        self._sum_sq_err += (err ** 2).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._sum_label_sq += (labels ** 2).sum(axis=0)
        self._sum_pred_sq += (predictions ** 2).sum(axis=0)
        self._sum_label_pred += (labels * predictions).sum(axis=0)

    def merge(self, other: "RegressionEvaluation") -> None:
        if not other._initialized:
            return
        if not self._initialized:
            self._init_accum(other.n_columns)
        for attr in ("_count", "_sum_abs_err", "_sum_sq_err", "_sum_label",
                     "_sum_pred", "_sum_label_sq", "_sum_pred_sq",
                     "_sum_label_pred"):
            setattr(self, attr, getattr(self, attr) + getattr(other, attr))

    # -- per-column metrics --------------------------------------------

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_sq_err[col] / max(self._count[col], 1))

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs_err[col] / max(self._count[col], 1))

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def correlation_r2(self, col: int) -> float:
        """Squared Pearson correlation (the reference's correlationR2)."""
        n = self._count[col]
        if n == 0:
            return 0.0
        cov = self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col] / n
        var_l = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        var_p = self._sum_pred_sq[col] - self._sum_pred[col] ** 2 / n
        denom = var_l * var_p
        return float(cov * cov / denom) if denom > 0 else 0.0

    def relative_squared_error(self, col: int) -> float:
        n = self._count[col]
        if n == 0:
            return 0.0
        var_l = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        return float(self._sum_sq_err[col] / var_l) if var_l > 0 else 0.0

    # -- aggregates -----------------------------------------------------

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(i) for i in range(self.n_columns)]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(i) for i in range(self.n_columns)]))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean([self.root_mean_squared_error(i) for i in range(self.n_columns)]))

    def average_correlation_r2(self) -> float:
        return float(np.mean([self.correlation_r2(i) for i in range(self.n_columns)]))

    def _name(self, i: int) -> str:
        if self.column_names and i < len(self.column_names):
            return self.column_names[i]
        return f"col_{i}"

    def stats(self) -> str:
        if not self._initialized:
            return "RegressionEvaluation: no data"
        lines = ["Column        MSE          MAE          RMSE         RSE          R^2"]
        for i in range(self.n_columns):
            lines.append(
                f"{self._name(i):<12} {self.mean_squared_error(i):<12.6g} "
                f"{self.mean_absolute_error(i):<12.6g} "
                f"{self.root_mean_squared_error(i):<12.6g} "
                f"{self.relative_squared_error(i):<12.6g} "
                f"{self.correlation_r2(i):<12.6g}")
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.stats()
