"""ROC curves + AUC (parity: reference ``eval/ROC.java``, ``ROCMultiClass``).

The reference accumulates thresholded TP/FP counts at ``thresholdSteps``
evenly-spaced thresholds so the curve is streamable and mergeable; we keep
that design (exact-AUC-from-all-scores would require holding every score).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class ROC:
    """Binary ROC.

    labels: [b] / [b,1] 0-1, or one-hot [b,2] (column 1 = positive class,
    as in the reference). predictions: matching probabilities.
    """

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = int(threshold_steps)
        # thresholds 0, 1/steps, ..., 1.0 inclusive
        self.thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        self._tp = np.zeros_like(self.thresholds, dtype=np.int64)
        self._fp = np.zeros_like(self.thresholds, dtype=np.int64)
        self._pos = 0
        self._neg = 0

    @staticmethod
    def _binary_views(labels, predictions) -> Tuple[np.ndarray, np.ndarray]:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
        if predictions.ndim == 2 and predictions.shape[1] == 2:
            # two-column probabilities with single-column labels: column 1 is
            # the positive class (reference convention)
            predictions = predictions[:, 1]
        y, p = labels.reshape(-1), predictions.reshape(-1)
        if y.shape[0] != p.shape[0]:
            raise ValueError(
                f"ROC.eval: {y.shape[0]} labels vs {p.shape[0]} predictions "
                "after flattening — shapes must describe the same examples "
                f"(labels {labels.shape}, predictions {predictions.shape})")
        return y, p

    def eval(self, labels, predictions, mask=None) -> None:
        y, p = self._binary_views(labels, predictions)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            y, p = y[keep], p[keep]
        pos = y > 0.5
        self._pos += int(pos.sum())
        self._neg += int((~pos).sum())
        # predicted positive at threshold t ⇔ p >= t  (vectorized over both
        # thresholds and examples)
        pred_pos = p[None, :] >= self.thresholds[:, None]
        self._tp += (pred_pos & pos[None, :]).sum(axis=1)
        self._fp += (pred_pos & ~pos[None, :]).sum(axis=1)

    def merge(self, other: "ROC") -> None:
        if other.threshold_steps != self.threshold_steps:
            raise ValueError("cannot merge ROC with different threshold steps")
        self._tp += other._tp
        self._fp += other._fp
        self._pos += other._pos
        self._neg += other._neg

    def get_roc_curve(self) -> List[Tuple[float, float, float]]:
        """[(threshold, fpr, tpr)] from threshold 0 → 1."""
        out = []
        for i, t in enumerate(self.thresholds):
            tpr = self._tp[i] / self._pos if self._pos else 0.0
            fpr = self._fp[i] / self._neg if self._neg else 0.0
            out.append((float(t), float(fpr), float(tpr)))
        return out

    def get_precision_recall_curve(self) -> List[Tuple[float, float, float]]:
        """[(threshold, precision, recall)]."""
        out = []
        for i, t in enumerate(self.thresholds):
            denom = self._tp[i] + self._fp[i]
            prec = self._tp[i] / denom if denom else 1.0
            rec = self._tp[i] / self._pos if self._pos else 0.0
            out.append((float(t), float(prec), float(rec)))
        return out

    def calculate_auc(self) -> float:
        """Trapezoidal area under (fpr, tpr), sorted by fpr ascending."""
        curve = self.get_roc_curve()
        pts = sorted((fpr, tpr) for _, fpr, tpr in curve)
        # ensure the curve spans [0,1] on the fpr axis
        if pts[0][0] > 0.0:
            pts.insert(0, (0.0, 0.0))
        if pts[-1][0] < 1.0:
            pts.append((1.0, 1.0))
        xs = np.array([p[0] for p in pts])
        ys = np.array([p[1] for p in pts])
        trapezoid = getattr(np, "trapezoid", None) or np.trapz
        return float(trapezoid(ys, xs))


class ROCMultiClass:
    """One-vs-all ROC per class (parity: reference ``ROCMultiClass.java``)."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = int(threshold_steps)
        self._per_class: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim != 2:
            raise ValueError("ROCMultiClass needs one-hot labels [b, c]")
        for c in range(labels.shape[1]):
            roc = self._per_class.setdefault(c, ROC(self.threshold_steps))
            roc.eval(labels[:, c], predictions[:, c], mask=mask)

    def calculate_auc(self, cls: int) -> float:
        return self._per_class[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        if not self._per_class:
            return 0.0
        return float(np.mean([r.calculate_auc() for r in self._per_class.values()]))

    def merge(self, other: "ROCMultiClass") -> None:
        for c, roc in other._per_class.items():
            if c in self._per_class:
                self._per_class[c].merge(roc)
            else:
                self._per_class[c] = roc
