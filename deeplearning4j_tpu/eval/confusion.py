"""Confusion matrix (parity: reference ``eval/ConfusionMatrix.java``)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Dense integer confusion matrix over a fixed class set.

    Rows = actual class, columns = predicted class — the same orientation as
    the reference's ``ConfusionMatrix.add(actual, predicted)``.
    """

    def __init__(self, classes: Sequence[int]):
        self.classes: List[int] = sorted(int(c) for c in classes)
        self._index: Dict[int, int] = {c: i for i, c in enumerate(self.classes)}
        n = len(self.classes)
        self.matrix = np.zeros((n, n), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.matrix[self._index[int(actual)], self._index[int(predicted)]] += count

    def grow_to(self, num_classes: int) -> None:
        """Extend the class set to [0, num_classes) preserving counts (used
        when integer labels reveal new classes in a later batch)."""
        n = len(self.classes)
        if num_classes <= n:
            return
        if self.classes != list(range(n)):
            raise ValueError("grow_to requires a contiguous 0..n-1 class set")
        new = np.zeros((num_classes, num_classes), dtype=np.int64)
        new[:n, :n] = self.matrix
        self.matrix = new
        self.classes = list(range(num_classes))
        self._index = {c: i for i, c in enumerate(self.classes)}

    def add_batch(self, actual: np.ndarray, predicted: np.ndarray,
                  weights: Optional[np.ndarray] = None) -> None:
        """Vectorized accumulation of a whole minibatch."""
        n = len(self.classes)
        idx = actual.astype(np.int64) * n + predicted.astype(np.int64)
        counts = np.bincount(idx, weights=weights, minlength=n * n)
        self.matrix += counts.reshape(n, n).astype(np.int64)

    def count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[self._index[int(actual)], self._index[int(predicted)]])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[self._index[int(cls)]].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, self._index[int(cls)]].sum())

    def total(self) -> int:
        return int(self.matrix.sum())

    def merge(self, other: "ConfusionMatrix") -> None:
        if other.classes != self.classes:
            raise ValueError("cannot merge confusion matrices over different class sets")
        self.matrix += other.matrix

    def to_csv(self) -> str:
        header = "actual\\predicted," + ",".join(str(c) for c in self.classes)
        rows = [header]
        for i, c in enumerate(self.classes):
            rows.append(str(c) + "," + ",".join(str(int(v)) for v in self.matrix[i]))
        return "\n".join(rows)

    def __str__(self) -> str:
        return self.to_csv()
