"""Dtype policy for TPU execution.

The reference (ND4J) has a single global data-type (float/double) set on the
Nd4j factory. On TPU the idiomatic split is: parameters and optimizer state in
float32, matmul/conv compute in bfloat16 (MXU native), reductions/softmax in
float32. This module provides a policy object threaded through layer apply
functions, plus a global default.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """What dtype to use where.

    param_dtype:   dtype parameters are stored in (float32 for stable updates).
    compute_dtype: dtype inputs/params are cast to for matmul/conv (bfloat16
                   keeps the MXU fed at full rate on TPU).
    output_dtype:  dtype activations are returned in (None = compute_dtype).
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = None

    def cast_to_compute(self, *arrays):
        out = tuple(
            a.astype(self.compute_dtype) if hasattr(a, "astype") else a for a in arrays
        )
        return out[0] if len(out) == 1 else out

    def cast_output(self, array):
        dt = self.output_dtype or self.compute_dtype
        return array.astype(dt)


FLOAT32 = DtypePolicy()
# Mixed precision: bf16 compute, f32 params — the TPU training default.
MIXED_BF16 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                         output_dtype=jnp.bfloat16)
FLOAT64 = DtypePolicy(param_dtype=jnp.float64, compute_dtype=jnp.float64)

_default_policy = FLOAT32


def default_policy() -> DtypePolicy:
    return _default_policy


def set_default_policy(policy: DtypePolicy) -> None:
    global _default_policy
    _default_policy = policy


def policy_from_name(name: str) -> DtypePolicy:
    name = name.lower()
    if name in ("float32", "f32", "single"):
        return FLOAT32
    if name in ("bfloat16", "bf16", "mixed", "mixed_bf16", "mixed_bfloat16"):
        return MIXED_BF16
    if name in ("float64", "f64", "double"):
        return FLOAT64
    raise ValueError(f"unknown dtype policy {name!r}")
