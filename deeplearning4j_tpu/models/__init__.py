"""Model zoo: canonical configs for the benchmark/parity suite.

The reference era has no in-tree model zoo (its examples repo served that
role); these builders produce the BASELINE.md configs:

  #1 LeNet-5 (MNIST, sequential)            — lenet()
  #2 ResNet-50 (ImageNet-class, DAG)        — resnet50() / resnet()
  #3 GravesLSTM char-RNN                    — char_rnn_lstm()
"""

from .lenet import lenet
from .resnet import resnet, resnet50, resnet_tiny
from .char_rnn import char_rnn_lstm
from .classic import alexnet, deep_autoencoder, vgg16
from .transformer import draft_transformer_lm, generate, transformer_lm

__all__ = ["lenet", "resnet", "resnet50", "resnet_tiny", "char_rnn_lstm",
           "alexnet", "vgg16", "deep_autoencoder", "transformer_lm",
           "draft_transformer_lm", "generate"]
