"""ResNet (BASELINE.md config #2): the DAG/MFU benchmark model.

Standard bottleneck ResNet (He et al. 2015) expressed in the framework's own
GraphBuilder DSL — conv(+BN) vertices, ElementWiseVertex residual sums,
projection shortcuts on stride-2 stage boundaries, global average pool head.

TPU-native notes: NHWC layout throughout; BN fuses into the conv epilogue
under XLA; the whole DAG becomes one jitted program, so depth costs no
dispatch overhead.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.graph import ElementWiseVertex, GraphBuilder
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, GlobalPoolingLayer,
    OutputLayer, SpaceToDepthLayer, SubsamplingLayer)


def _conv_bn(gb: GraphBuilder, name: str, inp: str, n_out: int,
             kernel, stride=(1, 1), activation: str = "relu") -> str:
    gb.add_layer(f"{name}_conv", ConvolutionLayer(
        n_out=n_out, kernel_size=tuple(kernel), stride=tuple(stride),
        border_mode="same", activation="identity", has_bias=False), inp)
    gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
    if activation == "identity":
        return f"{name}_bn"
    gb.add_layer(f"{name}_act", ActivationLayer(activation=activation),
                 f"{name}_bn")
    return f"{name}_act"


def _bottleneck(gb: GraphBuilder, name: str, inp: str, planes: int,
                stride: int, project: bool) -> str:
    """1x1 reduce → 3x3 → 1x1 expand (4×), + shortcut, relu."""
    c1 = _conv_bn(gb, f"{name}_a", inp, planes, (1, 1), (1, 1), "relu")
    c2 = _conv_bn(gb, f"{name}_b", c1, planes, (3, 3), (stride, stride), "relu")
    c3 = _conv_bn(gb, f"{name}_c", c2, planes * 4, (1, 1), (1, 1), "identity")
    if project:
        sc = _conv_bn(gb, f"{name}_proj", inp, planes * 4, (1, 1),
                      (stride, stride), "identity")
    else:
        sc = inp
    gb.add_vertex(f"{name}_sum", ElementWiseVertex(op="add"), c3, sc)
    gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                 f"{name}_sum")
    return f"{name}_relu"


def resnet(blocks: Sequence[int] = (3, 4, 6, 3), *,
           height: int = 224, width: int = 224, channels: int = 3,
           n_classes: int = 1000, width_base: int = 64,
           updater: str = "sgd", learning_rate: float = 0.1,
           momentum: float = 0.9, seed: int = 42, dtype: str = "mixed_bf16",
           stem: str = "conv7"):
    """Bottleneck ResNet as a ComputationGraphConfiguration.

    ``blocks=(3,4,6,3)`` → ResNet-50. Smaller test nets: ``blocks=(1,1)``,
    reduced ``width_base``/image size.

    ``stem="space_to_depth"`` lowers the 7×7/2 stem to an equivalent 4×4/1
    conv on a 2×2 space-to-depth input (the MLPerf-style MXU-friendly stem;
    ``fold_stem_7x7_to_s2d`` maps 7×7 weights onto it exactly).
    """
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater("nesterovs" if updater == "sgd" else updater)
         .momentum(momentum).learning_rate(learning_rate).dtype(dtype)
         .weight_init("RELU"))
    gb = b.graph_builder().add_inputs("in")
    if stem == "space_to_depth":
        gb.add_layer("stem_s2d", SpaceToDepthLayer(block_size=2), "in")
        stem = _conv_bn(gb, "stem", "stem_s2d", width_base, (4, 4), (1, 1),
                        "relu")
    else:
        stem = _conv_bn(gb, "stem", "in", width_base, (7, 7), (2, 2), "relu")
    gb.add_layer("stem_pool", SubsamplingLayer(
        kernel_size=(3, 3), stride=(2, 2), border_mode="same",
        pooling_type="max"), stem)
    cur = "stem_pool"
    for stage, n_blocks in enumerate(blocks):
        planes = width_base * (2 ** stage)
        for i in range(n_blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            project = (i == 0)  # channel change (and/or stride) at stage entry
            cur = _bottleneck(gb, f"s{stage}b{i}", cur, planes, stride, project)
    gb.add_layer("head_pool", GlobalPoolingLayer(pooling_type="avg"), cur)
    gb.add_layer("out", OutputLayer(n_out=n_classes, activation="softmax",
                                    loss="mcxent"), "head_pool")
    return (gb.set_outputs("out")
            .set_input_types(InputType.convolutional(height, width, channels))
            .build())


def resnet50(**kw):
    """ResNet-50 (ImageNet geometry by default)."""
    return resnet((3, 4, 6, 3), **kw)


def resnet_tiny(*, height: int = 32, width: int = 32, channels: int = 3,
                n_classes: int = 10, width_base: int = 16, **kw):
    """Two-block bottleneck ResNet at CIFAR geometry: the CPU-harness
    stand-in for the ResNet-50 bench path (same DAG shape — stem, stage
    boundaries, projection shortcuts — at ~1/400th the FLOPs), used by
    ``bench_input_pipeline`` and pipeline tests where compiling the full
    ImageNet config would dominate the measurement."""
    return resnet((1, 1), height=height, width=width, channels=channels,
                  n_classes=n_classes, width_base=width_base, **kw)


def fold_stem_7x7_to_s2d(w7: np.ndarray) -> np.ndarray:
    """Map 7×7/2 stem weights [7,7,C,O] (SAME pad → (2,3)) onto the exact
    equivalent 4×4/1 kernel [4,4,4C,O] over a 2×2 space-to-depth input
    (SAME pad → (1,2)); s2d channel order (di, dj, c).

    Derivation: output tap kh ∈ [0,7) reads x[2i + kh − 2]; writing
    kh − 2 = 2u + di (u ∈ [−1,2], di ∈ {0,1}) makes it a 4-tap conv over
    s2d rows with block-offset channel di — the (u=2, di=1) slot (kh=7)
    stays zero. Same for kw.
    """
    kh_, kw_, c, o = w7.shape
    if (kh_, kw_) != (7, 7):
        raise ValueError(f"expected a 7x7 kernel, got {w7.shape}")
    w4 = np.zeros((4, 4, 4 * c, o), dtype=w7.dtype)
    for kh in range(7):
        u, di = divmod(kh - 2, 2)
        for kw in range(7):
            v, dj = divmod(kw - 2, 2)
            ch = (di * 2 + dj) * c
            w4[u + 1, v + 1, ch:ch + c, :] = w7[kh, kw]
    return w4
