"""LeNet-5 (BASELINE.md config #1): the minimum end-to-end model."""

from __future__ import annotations

from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)


def lenet(height: int = 28, width: int = 28, channels: int = 1,
          n_classes: int = 10, *, updater: str = "adam",
          learning_rate: float = 1e-3, seed: int = 42, dtype: str = "float32"):
    """LeNet-5-style convnet as a MultiLayerConfiguration."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater).learning_rate(learning_rate)
            .dtype(dtype)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                    pooling_type="max"))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                    pooling_type="max"))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(height, width, channels))
            .build())
