"""Classic convnet + autoencoder families of the reference's era.

The reference keeps its model zoo in a separate examples repo; these
builders exercise the same config DSL the benchmarks use (lenet/resnet) on
the era's other canonical architectures. All NHWC, TPU dtype policy via
``dtype=``.
"""

from __future__ import annotations

from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (
    ConvolutionLayer, DenseLayer, LocalResponseNormalization,
    OutputLayer, SubsamplingLayer)


def alexnet(height: int = 224, width: int = 224, channels: int = 3,
            n_classes: int = 1000, *, updater: str = "sgd",
            learning_rate: float = 1e-2, seed: int = 42,
            dtype: str = "mixed_bf16"):
    """AlexNet (Krizhevsky 2012): 5 conv + LRN + 3 dense, single-tower."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater).learning_rate(learning_rate)
         .weight_init("relu")
         .dtype(dtype)
         .list()
         .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                 stride=(4, 4), padding=(2, 2),
                                 activation="relu"))
         .layer(LocalResponseNormalization())
         .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
         .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                 padding=(2, 2), activation="relu"))
         .layer(LocalResponseNormalization())
         .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
         .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                 padding=(1, 1), activation="relu"))
         .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                 padding=(1, 1), activation="relu"))
         .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                 padding=(1, 1), activation="relu"))
         .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
         .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
         .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
         .layer(OutputLayer(n_out=n_classes, activation="softmax",
                            loss="mcxent")))
    return b.set_input_type(
        InputType.convolutional(height, width, channels)).build()


_VGG16_PLAN = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))


def vgg16(height: int = 224, width: int = 224, channels: int = 3,
          n_classes: int = 1000, *, updater: str = "sgd",
          learning_rate: float = 1e-2, seed: int = 42,
          dtype: str = "mixed_bf16"):
    """VGG-16 (Simonyan & Zisserman 2014): 13 3×3 convs + 3 dense."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater).learning_rate(learning_rate)
         .weight_init("relu")
         .dtype(dtype)
         .list())
    for n_out, reps in _VGG16_PLAN:
        for _ in range(reps):
            b = b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                         padding=(1, 1), activation="relu"))
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    b = (b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
         .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
         .layer(OutputLayer(n_out=n_classes, activation="softmax",
                            loss="mcxent")))
    return b.set_input_type(
        InputType.convolutional(height, width, channels)).build()


def deep_autoencoder(n_in: int = 784,
                     hidden=(1000, 500, 250, 30), *,
                     updater: str = "adam", learning_rate: float = 1e-3,
                     seed: int = 42, dtype: str = "float32"):
    """Hinton & Salakhutdinov (2006) deep autoencoder — the architecture the
    reference trains on the curves dataset (use with
    ``CurvesDataSetIterator``, whose labels are the inputs)."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater).learning_rate(learning_rate)
         .dtype(dtype)
         .list())
    for n in hidden:                     # encoder
        b = b.layer(DenseLayer(n_out=n, activation="relu"))
    for n in reversed(hidden[:-1]):      # decoder
        b = b.layer(DenseLayer(n_out=n, activation="relu"))
    b = b.layer(OutputLayer(n_out=n_in, activation="sigmoid", loss="mse"))
    return b.set_input_type(InputType.feed_forward(n_in)).build()
