"""Char-RNN LSTM (BASELINE.md config #3): recurrent training + TBPTT +
streaming inference (the reference's GravesLSTM character-modelling setup)."""

from __future__ import annotations

from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import RnnOutputLayer
from ..nn.conf.recurrent import GravesLSTM


def char_rnn_lstm(vocab_size: int, *, hidden: int = 256, layers: int = 2,
                  tbptt_length: int = 50, updater: str = "adam",
                  learning_rate: float = 1e-3, seed: int = 42,
                  dtype: str = "float32"):
    """Stacked GravesLSTM char model as a MultiLayerConfiguration."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater).learning_rate(learning_rate)
         .dtype(dtype)
         .list())
    for _ in range(layers):
        b = b.layer(GravesLSTM(n_out=hidden, activation="tanh"))
    return (b.layer(RnnOutputLayer(n_out=vocab_size, activation="softmax",
                                   loss="mcxent"))
            .backprop_type("truncated_bptt")
            .t_bptt_forward_length(tbptt_length)
            .t_bptt_backward_length(tbptt_length)
            .set_input_type(InputType.recurrent(vocab_size))
            .build())
