"""Decoder-only transformer LM as a ComputationGraphConfiguration.

No reference analog (the reference is LSTM-era); this is the long-context
model family built from the framework's own DSL pieces: pre-norm blocks of
``SelfAttentionLayer`` + time-distributed FFN with ``ElementWiseVertex``
residual adds, trained like any other ComputationGraph (one jitted step,
works with remat, and the attention op auto-routes to the Pallas flash
kernel at long sequence lengths — see ops/flash_attention.py).

TPU-native layout: every vertex is time-axis-preserving ([b, t, f] end to
end — ``TimeDistributedDenseLayer`` einsums keep the time dim, no
flatten/rebuild reshapes), so under a sequence-sharded mesh
(``parallel.sequence.SequenceParallelGraphTrainer``) every op partitions
trivially over the time axis and attention rides the ring — no reshape of
a sharded dim, no gather.

Two input contracts:
  - default: one-hot [b, t, vocab] inputs + one-hot labels (``mcxent``) —
    fine for toy vocabularies and the existing parallel-trainer tests;
  - ``input_ids=True``: integer token ids [b, t] through an
    ``EmbeddingSequenceLayer`` gather, integer labels through
    ``sparse_mcxent`` — the REALISTIC-vocab path (a one-hot [b, t, V]
    host tensor at V ≫ 8 cannot survive; ids are 4 bytes/token however
    large V grows). Same math: one-hot @ W ≡ W[ids].
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..nn.conf.attention import SelfAttentionLayer
from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.graph import ElementWiseVertex, LayerVertex
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (EmbeddingSequenceLayer, LayerNormalization,
                              RnnOutputLayer)
from ..nn.conf.recurrent import TimeDistributedDenseLayer


def transformer_lm(vocab_size: int, *, n_layers: int = 4,
                   d_model: int = 256, n_heads: int = 4, d_ff: int = 1024,
                   updater: str = "adam", learning_rate: float = 3e-4,
                   seed: int = 42, dtype: str = "float32",
                   moe_experts: int = 0, moe_top_k: int = 2,
                   input_ids: bool = False,
                   max_cache_t: Optional[int] = None):
    """Causal LM: in-proj → n_layers × [ln → attention (+res) → ln → ffn
    (+res)] → final ln → vocab head.

    ``moe_experts > 0`` replaces every block's dense FFN with a top-k
    routed ``MoELayer`` (d_hidden=d_ff per expert, load-balancing aux loss
    included in training) — the expert-parallel model family; shard the
    expert dim over an ``ep`` mesh axis via
    ``parallel.expert.ExpertParallelGraphTrainer``.

    ``input_ids=True`` switches to the integer-id contract (see module
    docstring): feed [b, t] int32 ids, label with [b, t] int32 ids.

    ``max_cache_t`` arms every block's attention with a streaming K/V
    cache of that many positions — required for autoregressive decode
    (:func:`generate` / the paged serving engine); overflowing it slides
    the attention window (see ``SelfAttentionLayer.cache_overflow``)."""
    if d_model % n_heads:
        raise ValueError(f"d_model={d_model} not divisible by "
                         f"n_heads={n_heads}")
    from ..nn.conf.moe import MoELayer
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).updater(updater).learning_rate(learning_rate)
          .dtype(dtype)
          .graph_builder()
          .add_inputs("in"))
    if input_ids:
        gb.add_layer("embed",
                     EmbeddingSequenceLayer(n_in=vocab_size,
                                            n_out=d_model,
                                            activation="identity"), "in")
    else:
        gb.add_layer("embed",
                     TimeDistributedDenseLayer(n_in=vocab_size,
                                               n_out=d_model,
                                               activation="identity"), "in")
    prev = "embed"
    for i in range(n_layers):
        b = f"blk{i}"
        gb.add_layer(f"{b}_ln1", LayerNormalization(), prev)
        gb.add_layer(f"{b}_attn",
                     SelfAttentionLayer(n_in=d_model, n_out=d_model,
                                        n_heads=n_heads, causal=True,
                                        max_cache_t=max_cache_t),
                     f"{b}_ln1")
        gb.add_vertex(f"{b}_res1", ElementWiseVertex(op="add"),
                      prev, f"{b}_attn")
        gb.add_layer(f"{b}_ln2", LayerNormalization(), f"{b}_res1")
        if moe_experts > 0:
            gb.add_layer(f"{b}_moe",
                         MoELayer(n_in=d_model, n_out=d_model,
                                  d_hidden=d_ff, n_experts=moe_experts,
                                  top_k=moe_top_k),
                         f"{b}_ln2")
            ff_out = f"{b}_moe"
        else:
            gb.add_layer(f"{b}_ff1",
                         TimeDistributedDenseLayer(n_in=d_model,
                                                   n_out=d_ff,
                                                   activation="relu"),
                         f"{b}_ln2")
            gb.add_layer(f"{b}_ff2",
                         TimeDistributedDenseLayer(n_in=d_ff,
                                                   n_out=d_model,
                                                   activation="identity"),
                         f"{b}_ff1")
            ff_out = f"{b}_ff2"
        gb.add_vertex(f"{b}_res2", ElementWiseVertex(op="add"),
                      f"{b}_res1", ff_out)
        prev = f"{b}_res2"
    gb.add_layer("final_ln", LayerNormalization(), prev)
    gb.add_layer("out", RnnOutputLayer(
        n_in=d_model, n_out=vocab_size, activation="softmax",
        loss="sparse_mcxent" if input_ids else "mcxent"), "final_ln")
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(1 if input_ids else vocab_size))
    return gb.build()


# --------------------------------------------------------------------------
# autoregressive decode
# --------------------------------------------------------------------------


def attention_vertices(net) -> List[str]:
    """Topo-ordered names of the net's causal ``SelfAttentionLayer``
    vertices — the layers that own a K/V cache (dense or paged) during
    decode."""
    names = []
    for name in net.topo_order:
        v = net.conf.vertices[name]
        layer = v.layer if isinstance(v, LayerVertex) else None
        if isinstance(layer, SelfAttentionLayer) and layer.causal:
            names.append(name)
    return names


def filtered_probs_host(p: np.ndarray, temperature: float, top_k: int,
                        top_p: float) -> np.ndarray:
    """Host mirror of ``ops.sampling.filtered_probs`` for ONE row — the
    same temperature → top-k → renormalize → top-p → renormalize order,
    the same stable lower-id tie-breaking (documented in
    ``ops/sampling.py``; the host/device parity suite pins the pair)."""
    logits = np.log(np.maximum(p, 1e-30)) / float(temperature)
    logits -= logits.max()
    w = np.exp(logits)
    order = np.argsort(-w, kind="stable")
    if top_k and top_k > 0:
        w[order[int(top_k):]] = 0.0
    w /= max(w.sum(), 1e-30)
    if 0.0 < top_p < 1.0:
        w_desc = w[order]
        before = np.cumsum(w_desc) - w_desc
        w[order[before >= top_p]] = 0.0
        w /= max(w.sum(), 1e-30)
    return w


def sample_token(probs, temperature: float = 0.0, rng=None, *,
                 top_k: int = 0, top_p: float = 1.0) -> int:
    """Next-token choice from a softmax row — host-side, shared by the
    full-cache oracle (:func:`generate`) and the paged serving engine so
    the two paths CANNOT diverge in how they read the same distribution.
    ``temperature <= 0`` is greedy (argmax); otherwise an inverse-CDF
    draw (one uniform from ``rng``, a ``numpy.random.Generator``) over
    the temperature/top-k/top-p filtered distribution — the EXACT
    semantics of the on-device sampler ``ops.sampling.sample_tokens``
    (same filter order, same ascending-id inverse CDF), so host and
    device agree token-for-token at the same uniform."""
    p = np.asarray(probs, dtype=np.float64).reshape(-1)
    if temperature <= 0.0:
        return int(np.argmax(p))
    if rng is None:
        raise ValueError("temperature sampling needs an rng")
    w = filtered_probs_host(p, temperature, top_k, top_p)
    c = np.cumsum(w)
    gt = c > float(rng.random()) * c[-1]
    if gt.any():
        return int(np.argmax(gt))
    # u·total reached the top of the CDF (possible only through float
    # rounding): same last-positive-weight fallback as the device twin
    return int(np.max(np.nonzero(w > 0)[0]))


def generate(net, prompt_ids, max_new_tokens: int, *,
             temperature: float = 0.0, eos_id: Optional[int] = None,
             rng=None, top_k: int = 0, top_p: float = 1.0) -> np.ndarray:
    """Single-sequence full-cache autoregressive decode through the
    streaming ``rnn_time_step`` path — the offline API AND the parity
    oracle the continuous-batching serving engine is pinned bit-exact
    against (greedy; ``tests/test_decode.py``).

    The net must be an ids-mode ``transformer_lm`` built with
    ``max_cache_t`` set (the dense K/V window). Returns the generated ids
    as int32 (≤ ``max_new_tokens``; stops early at ``eos_id``, which is
    included in the output)."""
    from ..util.netutil import streaming_cache_limit
    limit = streaming_cache_limit(net)
    if limit is None:
        raise ValueError(
            "generate() needs streaming K/V caches — build the net with "
            "transformer_lm(..., max_cache_t=...)")
    prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
    if prompt.size < 1:
        raise ValueError("generate() needs a non-empty prompt")
    net.rnn_clear_previous_state()
    # the first window of the prompt goes in one chunk; any tail past
    # the window is fed token by token — eviction is chunk-granular
    # (the whole chunk's worth is evicted before its queries attend),
    # so single-token feeding is what gives every position the exact
    # (p - max_cache_t, p] sliding window
    first = min(len(prompt), limit)
    out = net.rnn_time_step(prompt[None, :first, None])
    for i in range(first, len(prompt)):
        out = net.rnn_time_step(prompt[None, i:i + 1, None])
    probs = np.asarray(out)[0, -1]
    toks: List[int] = []
    for i in range(int(max_new_tokens)):
        t = sample_token(probs, temperature, rng, top_k=top_k, top_p=top_p)
        toks.append(t)
        if (eos_id is not None and t == eos_id) \
                or i == int(max_new_tokens) - 1:
            break
        step = net.rnn_time_step(np.full((1, 1, 1), t, np.int32))
        probs = np.asarray(step)[0, -1]
    return np.asarray(toks, np.int32)


def oracle_stream_probs(net, token_ids) -> np.ndarray:
    """Per-position next-token distributions from the dense full-cache
    streaming path — the float32 quality oracle the int8 KV-page
    quantization gate compares against (``tests/test_prefix_cache.py``,
    ``bench.py``'s ``int8_logit_max_err``).

    Feeds ``token_ids`` through ``rnn_time_step`` with the same
    chunk-then-token schedule as :func:`generate` (first window in one
    chunk, tail token by token, so past-window positions see the exact
    sliding window) and returns ``[len(token_ids), V]`` float64 — row i
    is the model's distribution over the token FOLLOWING position i."""
    from ..util.netutil import streaming_cache_limit
    limit = streaming_cache_limit(net)
    if limit is None:
        raise ValueError(
            "oracle_stream_probs() needs streaming K/V caches — build "
            "the net with transformer_lm(..., max_cache_t=...)")
    ids = np.asarray(token_ids, np.int32).reshape(-1)
    if ids.size < 1:
        raise ValueError("oracle_stream_probs() needs at least one token")
    net.rnn_clear_previous_state()
    first = min(len(ids), limit)
    rows = [np.asarray(net.rnn_time_step(ids[None, :first, None]),
                       np.float64)[0]]
    for i in range(first, len(ids)):
        step = net.rnn_time_step(ids[None, i:i + 1, None])
        rows.append(np.asarray(step, np.float64)[0])
    return np.concatenate(rows, axis=0)


def paged_decode_forward(net, params, k_pools, v_pools, ids, page_tables,
                         write_slots, rel_pos):
    """ONE traced forward of an ids-mode ``transformer_lm`` graph in
    paged-decode mode: every causal attention vertex reads/writes the
    block pools through the lanes' page tables
    (``SelfAttentionLayer.apply_paged``); every other vertex applies
    exactly as in ``output()``. Pure w.r.t. its arguments, so the serving
    engine jits it once per (lanes, chunk) bucket and admission/
    retirement only ever change array CONTENTS.

    ids: ``[S, t_new]`` int32 (padded lanes: any value — their writes are
    dropped and their outputs ignored); page_tables: ``[S, P]``;
    write_slots: ``[S, t_new]`` view-relative slots (-1 = dropped);
    rel_pos: ``[S]``. Returns ``(probs [S, t_new, V], k_pools,
    v_pools)``.
    """
    attn = attention_vertices(net)
    if len(attn) != len(k_pools):
        raise ValueError(
            f"{len(k_pools)} pools for {len(attn)} attention vertices")
    pool_ix = {n: i for i, n in enumerate(attn)}
    k_pools, v_pools = list(k_pools), list(v_pools)
    acts = {net.conf.network_inputs[0]: ids[:, :, None]}
    mbs = net._minibatch_map(ids.shape[0])
    for name in net.topo_order:
        in_names = net.conf.vertex_inputs[name]
        i = pool_ix.get(name)
        if i is not None:
            layer = net.conf.vertices[name].layer
            out, k_pools[i], v_pools[i] = layer.apply_paged(
                params[name], acts[in_names[0]], k_pools[i], v_pools[i],
                page_tables, write_slots, rel_pos, policy=net.policy)
        else:
            out, _ = net._apply_vertex(name, params[name], acts, {}, None,
                                       train=False,
                                       minibatch=mbs[in_names[0]])
        acts[name] = out
    return acts[net.conf.network_outputs[0]], k_pools, v_pools


# --------------------------------------------------------------------------
# fused multi-token decode + speculative draft/verify (traced bodies)
# --------------------------------------------------------------------------


def draft_transformer_lm(vocab_size: int, *, d_model: int = 128,
                         n_heads: int = 4, d_ff: int = 512,
                         seed: int = 42, dtype: str = "float32",
                         max_cache_t: Optional[int] = None):
    """The in-tree DRAFT model family for speculative decoding: a
    2-layer ids-mode :func:`transformer_lm` over the SAME vocabulary as
    the target it drafts for (same input contract, same softmax head, so
    its filtered distributions are directly comparable in the
    accept/reject step). Train it however the target was trained — the
    serving engine only requires matching vocab + window."""
    return transformer_lm(vocab_size, n_layers=2, d_model=d_model,
                          n_heads=n_heads, d_ff=d_ff, seed=seed,
                          dtype=dtype, input_ids=True,
                          max_cache_t=max_cache_t)


def fused_decode_loop(net, params, k_pools, v_pools, last_tokens,
                      page_tables, rel_pos, active, budget, eos_ids,
                      temperature, top_k, top_p, uniforms):
    """N decode steps over the paged arena in ONE dispatch — the
    device-resident inner loop the serving engine jits per lane bucket
    (``uniforms [S, N]`` fixes N at trace time). Each inner step
    writes the lane's pending token's K/V (paged scatter), runs one
    paged forward (t_new=1, identical math to the host-ticked step, so
    greedy output stays bit-exact vs :func:`generate`), samples the next
    token ON DEVICE (``ops.sampling.sample_tokens``: greedy argmax or
    temperature/top-k/top-p inverse-CDF at that step's uniform), and
    folds the EOS/budget self-retire mask: a finished lane keeps
    computing (fixed shapes) but its writes turn to ``-1`` slots —
    dropped by the scatter, same sentinel discipline as padded lanes —
    and its outputs are marked invalid.

    last_tokens ``[S]``: each lane's pending (sampled-but-unwritten)
    token; rel_pos ``[S]``: its view-relative slot (the host pre-draws /
    pre-rotates pages for the WHOLE block, so slots advance contiguously
    ``rel_pos .. rel_pos+N-1``); active ``[S]``: padded lanes start
    retired; budget ``[S]``: tokens this lane may still emit (≤ N);
    eos_ids ``[S]`` (-1 = none); temperature/top_k/top_p ``[S]``
    per-lane sampling config.

    Returns ``(tokens [S, N], valid [S, N], n_emitted [S], done [S],
    k_pools, v_pools)`` — ``valid`` is a prefix mask; ``n_emitted`` is
    both the number of valid tokens AND the number of K/V slots the lane
    actually wrote (the host advances its position by exactly this).

    Two CPU-harness-measured costs shape the implementation: the loop
    is a ``while_loop`` (not ``scan``) so a block whose every lane
    self-retired stops computing instead of burning the remaining
    steps, and the filtered-sampling pipeline (two vocab argsorts per
    step) sits behind a ``lax.cond`` on "any lane sampling" — an
    all-greedy block (the common serving case) pays only the argmax."""
    import jax
    import jax.numpy as jnp

    from ..ops import sampling as _sampling

    n_steps = uniforms.shape[1]
    s = last_tokens.shape[0]
    any_sampled = jnp.any(temperature > 0)

    def pick(row, u):
        return jax.lax.cond(
            any_sampled,
            lambda: _sampling.sample_tokens(row, temperature, top_k,
                                            top_p, u),
            lambda: jnp.argmax(row, axis=-1).astype(jnp.int32))

    def cond_fn(st):
        i, _, _, _, done, _, _, _ = st
        return (i < n_steps) & jnp.logical_not(jnp.all(done))

    def body_fn(st):
        i, k_pools, v_pools, cur, done, n_emitted, toks, valid = st
        slot = jnp.where(done, jnp.int32(-1), rel_pos + i)
        probs, k_pools, v_pools = paged_decode_forward(
            net, params, k_pools, v_pools, cur[:, None], page_tables,
            slot[:, None], rel_pos + i)
        u = jax.lax.dynamic_index_in_dim(uniforms, i, axis=1,
                                         keepdims=False)
        tok = pick(probs[:, 0, :], u)
        emit = jnp.logical_not(done)
        n_emitted = n_emitted + emit.astype(jnp.int32)
        hit_eos = (eos_ids >= 0) & (tok == eos_ids)
        done = done | (emit & (hit_eos | (n_emitted >= budget)))
        cur = jnp.where(emit, tok, cur)
        toks = jax.lax.dynamic_update_index_in_dim(
            toks, jnp.where(emit, tok, -1), i, axis=1)
        valid = jax.lax.dynamic_update_index_in_dim(valid, emit, i,
                                                    axis=1)
        return (i + 1, k_pools, v_pools, cur, done, n_emitted, toks,
                valid)

    st = (jnp.int32(0), list(k_pools), list(v_pools),
          last_tokens.astype(jnp.int32), jnp.logical_not(active),
          jnp.zeros(s, jnp.int32), jnp.full((s, n_steps), -1, jnp.int32),
          jnp.zeros((s, n_steps), bool))
    (_, k_pools, v_pools, _, done, n_emitted, toks,
     valid) = jax.lax.while_loop(cond_fn, body_fn, st)
    return toks, valid, n_emitted, done, k_pools, v_pools


def draft_decode_loop(net, params, k_pools, v_pools, last_tokens,
                      page_tables, rel_pos, active, write_budget,
                      temperature, top_k, top_p, uniforms):
    """The draft half of a speculative block: K+1 fused steps of the
    (small) draft net over ITS OWN pools through the SHARED page tables.
    ``uniforms`` is ``[S, K+1]``; the scan feeds
    ``[pending, d_1 .. d_K]`` — K+1 inputs — so the draft writes K/V for
    ALL of them, including ``d_K`` (whose output is discarded). That
    last write is what keeps the draft cache gap-free after a
    fully-accepted block: target and draft frontiers always advance in
    lockstep, and rejected tokens' stale K/V sits beyond the causal mask
    until legitimately overwritten (the same discipline as the fused
    loop's dropped writes).

    ``write_budget [S]`` caps each lane's writes at the tokens it can
    still legitimately emit: slots past ``rel_pos + write_budget - 1``
    are dropped. Without the cap a lane near its max-tokens (or near
    the window edge) would scatter up to K useless slots past its last
    possible position — forcing page draws (and, at the window edge,
    PREMATURE EVICTION that would break within-window bit-exactness)
    for tokens that can never exist. Draft outputs past the budget are
    garbage-in-garbage-out: the host truncates to the budget anyway,
    and every position the host can keep attends only to written slots.

    Returns ``(draft_tokens [S, K], draft_dists [S, K, V], k_pools,
    v_pools)`` — ``draft_dists`` are the FILTERED distributions the
    draft sampled from (what the accept/reject ratio needs); greedy
    lanes ignore them."""
    import jax
    import jax.numpy as jnp

    from ..ops import sampling as _sampling

    k1 = uniforms.shape[1]                  # K + 1
    any_sampled = jnp.any(temperature > 0)

    def body(carry, xs):
        k_pools, v_pools, cur = carry
        i, u = xs
        slot = jnp.where(active & (i < write_budget), rel_pos + i,
                         jnp.int32(-1))
        probs, k_pools, v_pools = paged_decode_forward(
            net, params, k_pools, v_pools, cur[:, None], page_tables,
            slot[:, None], rel_pos + i)
        row = probs[:, 0, :]
        greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
        # all-greedy batches skip the filter pipeline AND its dist
        # output (the verify greedy branch never reads it)
        dist, tok = jax.lax.cond(
            any_sampled,
            lambda: (lambda d: (d, jnp.where(
                temperature > 0, _sampling.inverse_cdf(d, u), greedy))
            )(_sampling.filtered_probs(row, temperature, top_k, top_p)),
            lambda: (jnp.zeros_like(row), greedy))
        return (k_pools, v_pools, tok), (tok, dist)

    init = (list(k_pools), list(v_pools), last_tokens.astype(jnp.int32))
    (k_pools, v_pools, _), (toks, dists) = jax.lax.scan(
        body, init, (jnp.arange(k1, dtype=jnp.int32), uniforms.T))
    return (toks[:k1 - 1].T, dists[:k1 - 1].transpose(1, 0, 2),
            k_pools, v_pools)


def spec_verify(net, params, k_pools, v_pools, last_tokens, page_tables,
                rel_pos, active, write_budget, draft_tokens, draft_dists,
                temperature, top_k, top_p, u_accept, u_fix):
    """The verify half of a speculative block: ONE batched target pass
    over ``[pending, d_1 .. d_K]`` (K+1 positions — the paged chunk
    forward is bit-exact vs feeding them one at a time, which is what
    makes greedy speculative output identical to target-only decode),
    then accept/reject + bonus selection ON DEVICE (Leviathan et al.):

    - greedy lanes (``temperature <= 0``): accept ``d_i`` iff it equals
      the target argmax at its position; the first mismatch position
      emits the target argmax instead; a fully-accepted block emits the
      position-K argmax as the BONUS token;
    - sampled lanes: accept ``d_i`` with probability
      ``min(1, q(d_i)/p(d_i))`` (filtered target / filtered draft) at
      ``u_accept[:, i]``; the first rejection samples from the residual
      ``max(q - p, 0)`` (fallback to ``q`` when the residual has no
      mass) at ``u_fix``; the bonus is a plain draw from the filtered
      position-K target distribution.

    Returns ``(emitted [S, K+1], valid [S, K+1], accepts [S], k_pools,
    v_pools)``: ``valid[:, j] = j <= accepts`` (a lane always emits its
    accepted prefix plus exactly one correction-or-bonus token); the
    HOST applies per-request EOS/max-tokens truncation to the valid
    prefix — each speculative block is one host tick anyway, so
    self-retire masking buys nothing here, unlike the fused loop.
    ``write_budget`` caps writes exactly as in
    :func:`draft_decode_loop` (same rationale, same slots)."""
    import jax
    import jax.numpy as jnp

    from ..ops import sampling as _sampling

    s, k = draft_tokens.shape
    k1 = k + 1
    ids = jnp.concatenate([last_tokens[:, None].astype(jnp.int32),
                           draft_tokens.astype(jnp.int32)], axis=1)
    offs = jnp.arange(k1, dtype=jnp.int32)[None, :]
    wslots = jnp.where(active[:, None] & (offs < write_budget[:, None]),
                       rel_pos[:, None] + offs, jnp.int32(-1))
    probs, k_pools, v_pools = paged_decode_forward(
        net, params, k_pools, v_pools, ids, page_tables, wslots, rel_pos)
    v = probs.shape[-1]
    t_hat = jnp.argmax(probs, axis=-1).astype(jnp.int32)      # [S, K+1]
    greedy = temperature <= 0
    acc_greedy = draft_tokens == t_hat[:, :k]

    def sampled_ops():
        rep = lambda a: jnp.repeat(a, k1, axis=0)             # noqa: E731
        q = _sampling.filtered_probs(probs.reshape(s * k1, v),
                                     rep(temperature), rep(top_k),
                                     rep(top_p)).reshape(s, k1, v)
        q_d = jnp.take_along_axis(q[:, :k, :], draft_tokens[:, :, None],
                                  axis=-1)[..., 0]            # [S, K]
        p_d = jnp.take_along_axis(draft_dists, draft_tokens[:, :, None],
                                  axis=-1)[..., 0]
        acc_sampled = u_accept < jnp.minimum(
            q_d / jnp.maximum(p_d, 1e-30), 1.0)
        resid = jnp.maximum(q[:, :k, :] - draft_dists, 0.0)
        has_mass = jnp.sum(resid, axis=-1, keepdims=True) > 0
        resid = jnp.where(has_mass, resid, q[:, :k, :])
        fix_dist = jnp.concatenate([resid, q[:, k:, :]],
                                   axis=1)                    # [S, K+1, V]
        fix_sampled = _sampling.inverse_cdf(
            fix_dist.reshape(s * k1, v),
            u_fix.reshape(s * k1)).reshape(s, k1)
        return (jnp.where(greedy[:, None], acc_greedy, acc_sampled),
                jnp.where(greedy[:, None], t_hat, fix_sampled))

    # all-greedy batches skip the filter/residual pipeline entirely
    accept, fix = jax.lax.cond(jnp.any(temperature > 0), sampled_ops,
                               lambda: (acc_greedy, t_hat))
    accepts = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                      axis=1)                                 # [S] 0..K
    fix_at_a = jnp.take_along_axis(fix, accepts[:, None], axis=1)
    d_pad = jnp.concatenate([draft_tokens,
                             jnp.zeros((s, 1), jnp.int32)], axis=1)
    j = jnp.arange(k1, dtype=jnp.int32)[None, :]
    emitted = jnp.where(j < accepts[:, None], d_pad,
                        jnp.where(j == accepts[:, None], fix_at_a, -1))
    valid = (j <= accepts[:, None]) & active[:, None]
    return emitted, valid, accepts, k_pools, v_pools
