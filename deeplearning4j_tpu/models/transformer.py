"""Decoder-only transformer LM as a ComputationGraphConfiguration.

No reference analog (the reference is LSTM-era); this is the long-context
model family built from the framework's own DSL pieces: pre-norm blocks of
``SelfAttentionLayer`` + time-distributed FFN with ``ElementWiseVertex``
residual adds, trained like any other ComputationGraph (one jitted step,
works with remat, and the attention op auto-routes to the Pallas flash
kernel at long sequence lengths — see ops/flash_attention.py).

TPU-native layout: every vertex is time-axis-preserving ([b, t, f] end to
end — ``TimeDistributedDenseLayer`` einsums keep the time dim, no
flatten/rebuild reshapes), so under a sequence-sharded mesh
(``parallel.sequence.SequenceParallelGraphTrainer``) every op partitions
trivially over the time axis and attention rides the ring — no reshape of
a sharded dim, no gather.

Two input contracts:
  - default: one-hot [b, t, vocab] inputs + one-hot labels (``mcxent``) —
    fine for toy vocabularies and the existing parallel-trainer tests;
  - ``input_ids=True``: integer token ids [b, t] through an
    ``EmbeddingSequenceLayer`` gather, integer labels through
    ``sparse_mcxent`` — the REALISTIC-vocab path (a one-hot [b, t, V]
    host tensor at V ≫ 8 cannot survive; ids are 4 bytes/token however
    large V grows). Same math: one-hot @ W ≡ W[ids].
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..nn.conf.attention import SelfAttentionLayer
from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.graph import ElementWiseVertex, LayerVertex
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (EmbeddingSequenceLayer, LayerNormalization,
                              RnnOutputLayer)
from ..nn.conf.recurrent import TimeDistributedDenseLayer


def transformer_lm(vocab_size: int, *, n_layers: int = 4,
                   d_model: int = 256, n_heads: int = 4, d_ff: int = 1024,
                   updater: str = "adam", learning_rate: float = 3e-4,
                   seed: int = 42, dtype: str = "float32",
                   moe_experts: int = 0, moe_top_k: int = 2,
                   input_ids: bool = False,
                   max_cache_t: Optional[int] = None):
    """Causal LM: in-proj → n_layers × [ln → attention (+res) → ln → ffn
    (+res)] → final ln → vocab head.

    ``moe_experts > 0`` replaces every block's dense FFN with a top-k
    routed ``MoELayer`` (d_hidden=d_ff per expert, load-balancing aux loss
    included in training) — the expert-parallel model family; shard the
    expert dim over an ``ep`` mesh axis via
    ``parallel.expert.ExpertParallelGraphTrainer``.

    ``input_ids=True`` switches to the integer-id contract (see module
    docstring): feed [b, t] int32 ids, label with [b, t] int32 ids.

    ``max_cache_t`` arms every block's attention with a streaming K/V
    cache of that many positions — required for autoregressive decode
    (:func:`generate` / the paged serving engine); overflowing it slides
    the attention window (see ``SelfAttentionLayer.cache_overflow``)."""
    if d_model % n_heads:
        raise ValueError(f"d_model={d_model} not divisible by "
                         f"n_heads={n_heads}")
    from ..nn.conf.moe import MoELayer
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).updater(updater).learning_rate(learning_rate)
          .dtype(dtype)
          .graph_builder()
          .add_inputs("in"))
    if input_ids:
        gb.add_layer("embed",
                     EmbeddingSequenceLayer(n_in=vocab_size,
                                            n_out=d_model,
                                            activation="identity"), "in")
    else:
        gb.add_layer("embed",
                     TimeDistributedDenseLayer(n_in=vocab_size,
                                               n_out=d_model,
                                               activation="identity"), "in")
    prev = "embed"
    for i in range(n_layers):
        b = f"blk{i}"
        gb.add_layer(f"{b}_ln1", LayerNormalization(), prev)
        gb.add_layer(f"{b}_attn",
                     SelfAttentionLayer(n_in=d_model, n_out=d_model,
                                        n_heads=n_heads, causal=True,
                                        max_cache_t=max_cache_t),
                     f"{b}_ln1")
        gb.add_vertex(f"{b}_res1", ElementWiseVertex(op="add"),
                      prev, f"{b}_attn")
        gb.add_layer(f"{b}_ln2", LayerNormalization(), f"{b}_res1")
        if moe_experts > 0:
            gb.add_layer(f"{b}_moe",
                         MoELayer(n_in=d_model, n_out=d_model,
                                  d_hidden=d_ff, n_experts=moe_experts,
                                  top_k=moe_top_k),
                         f"{b}_ln2")
            ff_out = f"{b}_moe"
        else:
            gb.add_layer(f"{b}_ff1",
                         TimeDistributedDenseLayer(n_in=d_model,
                                                   n_out=d_ff,
                                                   activation="relu"),
                         f"{b}_ln2")
            gb.add_layer(f"{b}_ff2",
                         TimeDistributedDenseLayer(n_in=d_ff,
                                                   n_out=d_model,
                                                   activation="identity"),
                         f"{b}_ff1")
            ff_out = f"{b}_ff2"
        gb.add_vertex(f"{b}_res2", ElementWiseVertex(op="add"),
                      f"{b}_res1", ff_out)
        prev = f"{b}_res2"
    gb.add_layer("final_ln", LayerNormalization(), prev)
    gb.add_layer("out", RnnOutputLayer(
        n_in=d_model, n_out=vocab_size, activation="softmax",
        loss="sparse_mcxent" if input_ids else "mcxent"), "final_ln")
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(1 if input_ids else vocab_size))
    return gb.build()


# --------------------------------------------------------------------------
# autoregressive decode
# --------------------------------------------------------------------------


def attention_vertices(net) -> List[str]:
    """Topo-ordered names of the net's causal ``SelfAttentionLayer``
    vertices — the layers that own a K/V cache (dense or paged) during
    decode."""
    names = []
    for name in net.topo_order:
        v = net.conf.vertices[name]
        layer = v.layer if isinstance(v, LayerVertex) else None
        if isinstance(layer, SelfAttentionLayer) and layer.causal:
            names.append(name)
    return names


def sample_token(probs, temperature: float = 0.0, rng=None) -> int:
    """Next-token choice from a softmax row — host-side, shared by the
    full-cache oracle (:func:`generate`) and the paged serving engine so
    the two paths CANNOT diverge in how they read the same distribution.
    ``temperature <= 0`` is greedy (argmax); otherwise softmax sampling at
    the given temperature from ``rng`` (a ``numpy.random.Generator``)."""
    p = np.asarray(probs, dtype=np.float64).reshape(-1)
    if temperature <= 0.0:
        return int(np.argmax(p))
    if rng is None:
        raise ValueError("temperature sampling needs an rng")
    logits = np.log(np.maximum(p, 1e-30)) / float(temperature)
    logits -= logits.max()
    e = np.exp(logits)
    e /= e.sum()
    return int(rng.choice(len(e), p=e))


def generate(net, prompt_ids, max_new_tokens: int, *,
             temperature: float = 0.0, eos_id: Optional[int] = None,
             rng=None) -> np.ndarray:
    """Single-sequence full-cache autoregressive decode through the
    streaming ``rnn_time_step`` path — the offline API AND the parity
    oracle the continuous-batching serving engine is pinned bit-exact
    against (greedy; ``tests/test_decode.py``).

    The net must be an ids-mode ``transformer_lm`` built with
    ``max_cache_t`` set (the dense K/V window). Returns the generated ids
    as int32 (≤ ``max_new_tokens``; stops early at ``eos_id``, which is
    included in the output)."""
    from ..util.netutil import streaming_cache_limit
    limit = streaming_cache_limit(net)
    if limit is None:
        raise ValueError(
            "generate() needs streaming K/V caches — build the net with "
            "transformer_lm(..., max_cache_t=...)")
    prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
    if prompt.size < 1:
        raise ValueError("generate() needs a non-empty prompt")
    net.rnn_clear_previous_state()
    # the first window of the prompt goes in one chunk; any tail past
    # the window is fed token by token — eviction is chunk-granular
    # (the whole chunk's worth is evicted before its queries attend),
    # so single-token feeding is what gives every position the exact
    # (p - max_cache_t, p] sliding window
    first = min(len(prompt), limit)
    out = net.rnn_time_step(prompt[None, :first, None])
    for i in range(first, len(prompt)):
        out = net.rnn_time_step(prompt[None, i:i + 1, None])
    probs = np.asarray(out)[0, -1]
    toks: List[int] = []
    for i in range(int(max_new_tokens)):
        t = sample_token(probs, temperature, rng)
        toks.append(t)
        if (eos_id is not None and t == eos_id) \
                or i == int(max_new_tokens) - 1:
            break
        step = net.rnn_time_step(np.full((1, 1, 1), t, np.int32))
        probs = np.asarray(step)[0, -1]
    return np.asarray(toks, np.int32)


def paged_decode_forward(net, params, k_pools, v_pools, ids, page_tables,
                         write_slots, rel_pos):
    """ONE traced forward of an ids-mode ``transformer_lm`` graph in
    paged-decode mode: every causal attention vertex reads/writes the
    block pools through the lanes' page tables
    (``SelfAttentionLayer.apply_paged``); every other vertex applies
    exactly as in ``output()``. Pure w.r.t. its arguments, so the serving
    engine jits it once per (lanes, chunk) bucket and admission/
    retirement only ever change array CONTENTS.

    ids: ``[S, t_new]`` int32 (padded lanes: any value — their writes are
    dropped and their outputs ignored); page_tables: ``[S, P]``;
    write_slots: ``[S, t_new]`` view-relative slots (-1 = dropped);
    rel_pos: ``[S]``. Returns ``(probs [S, t_new, V], k_pools,
    v_pools)``.
    """
    attn = attention_vertices(net)
    if len(attn) != len(k_pools):
        raise ValueError(
            f"{len(k_pools)} pools for {len(attn)} attention vertices")
    pool_ix = {n: i for i, n in enumerate(attn)}
    k_pools, v_pools = list(k_pools), list(v_pools)
    acts = {net.conf.network_inputs[0]: ids[:, :, None]}
    mbs = net._minibatch_map(ids.shape[0])
    for name in net.topo_order:
        in_names = net.conf.vertex_inputs[name]
        i = pool_ix.get(name)
        if i is not None:
            layer = net.conf.vertices[name].layer
            out, k_pools[i], v_pools[i] = layer.apply_paged(
                params[name], acts[in_names[0]], k_pools[i], v_pools[i],
                page_tables, write_slots, rel_pos, policy=net.policy)
        else:
            out, _ = net._apply_vertex(name, params[name], acts, {}, None,
                                       train=False,
                                       minibatch=mbs[in_names[0]])
        acts[name] = out
    return acts[net.conf.network_outputs[0]], k_pools, v_pools
