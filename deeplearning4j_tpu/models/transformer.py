"""Decoder-only transformer LM as a ComputationGraphConfiguration.

No reference analog (the reference is LSTM-era); this is the long-context
model family built from the framework's own DSL pieces: pre-norm blocks of
``SelfAttentionLayer`` + time-distributed FFN with ``ElementWiseVertex``
residual adds, trained like any other ComputationGraph (one jitted step,
works with remat, and the attention op auto-routes to the Pallas flash
kernel at long sequence lengths — see ops/flash_attention.py).

TPU-native layout: every vertex is time-axis-preserving ([b, t, f] end to
end — ``TimeDistributedDenseLayer`` einsums keep the time dim, no
flatten/rebuild reshapes), so under a sequence-sharded mesh
(``parallel.sequence.SequenceParallelGraphTrainer``) every op partitions
trivially over the time axis and attention rides the ring — no reshape of
a sharded dim, no gather.

Two input contracts:
  - default: one-hot [b, t, vocab] inputs + one-hot labels (``mcxent``) —
    fine for toy vocabularies and the existing parallel-trainer tests;
  - ``input_ids=True``: integer token ids [b, t] through an
    ``EmbeddingSequenceLayer`` gather, integer labels through
    ``sparse_mcxent`` — the REALISTIC-vocab path (a one-hot [b, t, V]
    host tensor at V ≫ 8 cannot survive; ids are 4 bytes/token however
    large V grows). Same math: one-hot @ W ≡ W[ids].
"""

from __future__ import annotations

from ..nn.conf.attention import SelfAttentionLayer
from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.graph import ElementWiseVertex
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (EmbeddingSequenceLayer, LayerNormalization,
                              RnnOutputLayer)
from ..nn.conf.recurrent import TimeDistributedDenseLayer


def transformer_lm(vocab_size: int, *, n_layers: int = 4,
                   d_model: int = 256, n_heads: int = 4, d_ff: int = 1024,
                   updater: str = "adam", learning_rate: float = 3e-4,
                   seed: int = 42, dtype: str = "float32",
                   moe_experts: int = 0, moe_top_k: int = 2,
                   input_ids: bool = False):
    """Causal LM: in-proj → n_layers × [ln → attention (+res) → ln → ffn
    (+res)] → final ln → vocab head.

    ``moe_experts > 0`` replaces every block's dense FFN with a top-k
    routed ``MoELayer`` (d_hidden=d_ff per expert, load-balancing aux loss
    included in training) — the expert-parallel model family; shard the
    expert dim over an ``ep`` mesh axis via
    ``parallel.expert.ExpertParallelGraphTrainer``.

    ``input_ids=True`` switches to the integer-id contract (see module
    docstring): feed [b, t] int32 ids, label with [b, t] int32 ids."""
    if d_model % n_heads:
        raise ValueError(f"d_model={d_model} not divisible by "
                         f"n_heads={n_heads}")
    from ..nn.conf.moe import MoELayer
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).updater(updater).learning_rate(learning_rate)
          .dtype(dtype)
          .graph_builder()
          .add_inputs("in"))
    if input_ids:
        gb.add_layer("embed",
                     EmbeddingSequenceLayer(n_in=vocab_size,
                                            n_out=d_model,
                                            activation="identity"), "in")
    else:
        gb.add_layer("embed",
                     TimeDistributedDenseLayer(n_in=vocab_size,
                                               n_out=d_model,
                                               activation="identity"), "in")
    prev = "embed"
    for i in range(n_layers):
        b = f"blk{i}"
        gb.add_layer(f"{b}_ln1", LayerNormalization(), prev)
        gb.add_layer(f"{b}_attn",
                     SelfAttentionLayer(n_in=d_model, n_out=d_model,
                                        n_heads=n_heads, causal=True),
                     f"{b}_ln1")
        gb.add_vertex(f"{b}_res1", ElementWiseVertex(op="add"),
                      prev, f"{b}_attn")
        gb.add_layer(f"{b}_ln2", LayerNormalization(), f"{b}_res1")
        if moe_experts > 0:
            gb.add_layer(f"{b}_moe",
                         MoELayer(n_in=d_model, n_out=d_model,
                                  d_hidden=d_ff, n_experts=moe_experts,
                                  top_k=moe_top_k),
                         f"{b}_ln2")
            ff_out = f"{b}_moe"
        else:
            gb.add_layer(f"{b}_ff1",
                         TimeDistributedDenseLayer(n_in=d_model,
                                                   n_out=d_ff,
                                                   activation="relu"),
                         f"{b}_ln2")
            gb.add_layer(f"{b}_ff2",
                         TimeDistributedDenseLayer(n_in=d_ff,
                                                   n_out=d_model,
                                                   activation="identity"),
                         f"{b}_ff1")
            ff_out = f"{b}_ff2"
        gb.add_vertex(f"{b}_res2", ElementWiseVertex(op="add"),
                      f"{b}_res1", ff_out)
        prev = f"{b}_res2"
    gb.add_layer("final_ln", LayerNormalization(), prev)
    gb.add_layer("out", RnnOutputLayer(
        n_in=d_model, n_out=vocab_size, activation="softmax",
        loss="sparse_mcxent" if input_ids else "mcxent"), "final_ln")
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(1 if input_ids else vocab_size))
    return gb.build()
