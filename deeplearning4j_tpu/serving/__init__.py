"""Model serving: HTTP inference endpoint over ``output()``.

Parity: reference ``dl4j-streaming``'s serving route
(``streaming/routes/DL4jServeRouteBuilder.java`` — Camel route feeding
records to a loaded model and publishing predictions) and the record
serde (``serde/RecordSerializer.java``). TPU-native replacement: a
dependency-free HTTP server with request micro-batching (batches amortize
dispatch and keep the MXU fed) and hot model swap.
"""

from .decode import (DecodeRequest, DecodeScheduler, PagedDecodeEngine,
                     SchedulerDraining, SchedulerSaturated)
from .fleet import FleetRouter, ReplicaAgent
from .kv_cache import PagedKVArena, PageAllocator
from .server import InferenceServer

__all__ = ["InferenceServer", "PagedDecodeEngine", "DecodeScheduler",
           "DecodeRequest", "PagedKVArena", "PageAllocator",
           "SchedulerSaturated", "SchedulerDraining",
           "FleetRouter", "ReplicaAgent"]
