"""Paged KV-cache arena: preallocated block pools + a page allocator.

The serving-side memory manager behind continuous-batching decode
(PAPERS: vLLM/SOSP'23). Instead of a monolithic ``[b, max_t, f]`` cache
per sequence — whose worst-case length must be reserved up front and
whose slots idle whenever a sequence is shorter — K/V live in per-layer
``[num_pages, page_size, heads, head_dim]`` block pools shared by every
in-flight sequence. Each sequence owns an ordered page table of physical
page ids; pages are handed out lazily as decode advances and returned to
the free list the moment the sequence retires, so HBM holds exactly the
tokens that exist, not the tokens that might.

Two-level accounting:

- **reservation** (admission control): a sequence reserves its worst-case
  page count when admitted — ``ceil((prompt + max_new_tokens) /
  page_size)`` capped at ``pages_per_seq`` — so a RUNNING sequence can
  never deadlock waiting for a page another running sequence holds.
  Reservations are counts, not physical pages.
- **draw** (lazy allocation): physical pages leave the free list one at a
  time, against the reservation, as the sequence actually grows.

Cross-request prefix caching (PAPERS: RadixAttention/SGLang) extends the
allocator with REFERENCE COUNTS: a page is live while any owner — a lane
or the :class:`PrefixIndex` — holds a reference, and returns to the free
list only at refcount 0. The index maps full-page-aligned token prefixes
to page chains; an admission whose prefix is resident retains the shared
pages into its table and skips their prefill. Cached-but-unpinned chains
count as *reclaimable*: the reservation invariant becomes ``reserved <=
free + reclaimable`` and ``draw()`` evicts the LRU unpinned chain leaf
when the free list runs dry. Writes never target shared pages (sharing
is full-page only; tails re-prefill from the page boundary), so
copy-on-write degenerates to a metadata detach: a window-evicting lane
releases its reference on a shared page and draws a private tail instead
of recycling in place (``kv_pages_cow_total``).

Sliding-window overflow is PAGE EVICTION: once a sequence holds
``pages_per_seq`` pages, its oldest page is recycled as the new tail
(the page table rotates, the view base advances by ``page_size``) —
the decode-arena analog of the dense cache's per-token eviction in
``SelfAttentionLayer._apply_streaming``, accounted in
``kv_pages_evicted_total``.

Thread-safety: the allocator locks itself (submit threads reserve while
the decode loop draws); the prefix index shares the allocator's RLock so
lookup→admit and draw→reclaim compose atomically. The pools are owned by
the decode engine, which mutates them only under the scheduler's
dispatch lock.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..util import metrics as _metrics

__all__ = ["PageAllocator", "PagedKVArena", "PrefixIndex"]

# kv_page_refcount histogram buckets: refcounts are small integers
# (1 = private, 2+ = shared); powers of two cover fan-out up to a
# 64-way-shared system prompt without per-value series blowup.
_REFCOUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class PageAllocator:
    """Refcounted free-list allocator over ``num_pages`` physical pages
    with reservation accounting (see module docstring).

    ``draw()`` hands a page out at refcount 1; ``retain()`` adds a
    reference (a prefix-cache hit mapping a shared page, or the index
    itself caching a chain); ``free()`` releases references and returns
    a page to the free list only when the last one drops. With a
    :class:`PrefixIndex` attached, cached-but-unpinned pages are
    *reclaimable* and extend admission capacity: ``reserved <= free +
    reclaimable`` is the invariant that keeps ``draw()`` infallible.
    """

    def __init__(self, num_pages: int,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = int(num_pages)
        self._free = deque(range(self.num_pages))
        self._refcount = [0] * self.num_pages
        self._shared = 0          # pages with refcount >= 2
        self._reserved = 0
        self._index: Optional["PrefixIndex"] = None
        # RLock: PrefixIndex methods run under this lock and call back
        # into the unlocked _retain/_release internals; the engine may
        # also hold it across lookup+admit to make a hit-admission atomic
        self._lock = threading.RLock()
        reg = registry if registry is not None else _metrics.REGISTRY
        self._m_evicted = reg.counter(
            "kv_pages_evicted_total",
            "KV pages recycled by sliding-window eviction")
        self._m_cow = reg.counter(
            "kv_pages_cow_total",
            "Shared KV pages detached copy-on-write at window eviction "
            "(reference released, private tail drawn instead)")
        self._m_refcount = reg.histogram(
            "kv_page_refcount",
            "Page reference count observed at each retain()",
            buckets=_REFCOUNT_BUCKETS)
        # weakly bound callbacks: on a SHARED registry the newest arena's
        # gauges win (per-server registries are the default, as with the
        # serving gauges), and a retired allocator is collectable — a
        # dead ref raises, which drops the series at exposition
        ref = weakref.ref(self)

        def _sample(attr):
            def fn():
                alloc = ref()
                if alloc is None:
                    raise LookupError("allocator retired")
                return float(getattr(alloc, attr))
            return fn

        reg.gauge(
            "kv_pages_in_use",
            "KV arena pages currently owned by live sequences"
        ).set_function(_sample("pages_in_use"))
        reg.gauge(
            "kv_pages_reserved",
            "KV arena pages reserved by admitted sequences but not yet "
            "drawn").set_function(_sample("reserved"))
        reg.gauge(
            "kv_pages_shared",
            "KV pages referenced by more than one owner (lanes and/or "
            "the prefix index)").set_function(_sample("shared_pages"))

    def attach_index(self, index: "PrefixIndex") -> None:
        self._index = index

    # -- unlocked internals (caller holds self._lock) ------------------

    def _reclaimable_locked(self) -> int:
        return self._index.reclaimable if self._index is not None else 0

    def _retain_locked(self, page: int) -> None:
        if not (0 <= page < self.num_pages):
            raise ValueError(f"retain() of unknown page {page}")
        rc = self._refcount[page]
        if rc < 1:
            raise ValueError(f"retain() of free page {page}")
        self._refcount[page] = rc + 1
        if rc == 1:
            self._shared += 1
            if self._index is not None:
                self._index._on_pin(page)
        self._m_refcount.observe(float(rc + 1))

    def _release_locked(self, pages: Sequence[int]) -> None:
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise ValueError(f"free() of unknown page {p}")
            rc = self._refcount[p] - 1
            if rc < 0:
                raise ValueError(f"free() of unreferenced page {p}")
            self._refcount[p] = rc
            if rc == 0:
                self._free.append(p)
            elif rc == 1:
                self._shared -= 1
                if self._index is not None:
                    self._index._on_unpin(p)

    # -- public API ----------------------------------------------------

    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    @property
    def reserved(self) -> int:
        with self._lock:
            return self._reserved

    @property
    def shared_pages(self) -> int:
        with self._lock:
            return self._shared

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refcount[page]

    def available(self) -> int:
        """Pages an admission could still reserve (reclaimable cached
        chains count — draw() evicts them on demand)."""
        with self._lock:
            return (len(self._free) + self._reclaimable_locked()
                    - self._reserved)

    def reserve(self, n: int) -> bool:
        """Reserve ``n`` pages for a sequence about to be admitted.
        False (and no state change) when the arena cannot guarantee
        them."""
        with self._lock:
            if n > (len(self._free) + self._reclaimable_locked()
                    - self._reserved):
                return False
            self._reserved += n
            return True

    def admit(self, need: int, retain_pages: Sequence[int] = ()) -> bool:
        """Atomic prefix-hit admission: retain ``retain_pages`` (the
        covered prefix chain) AND reserve ``need`` uncovered pages, or do
        neither. The check runs AFTER the retains because pinning a
        cached chain removes it from the reclaimable pool — an admission
        that covers its whole prompt (``need == 0``) can still fail when
        pinning would break ``reserved <= free + reclaimable``."""
        with self._lock:
            taken: List[int] = []
            try:
                for p in retain_pages:
                    self._retain_locked(p)
                    taken.append(p)
            except ValueError:
                self._release_locked(taken)
                return False
            if need > (len(self._free) + self._reclaimable_locked()
                       - self._reserved):
                self._release_locked(taken)
                return False
            self._reserved += need
            return True

    def retain(self, page: int) -> None:
        """Add a reference to a live page (prefix-cache sharing)."""
        with self._lock:
            self._retain_locked(page)

    def unreserve(self, n: int) -> None:
        """Return ``n`` unused reservations (early retirement: EOS before
        max_new_tokens, or a capped window that never grew that far)."""
        with self._lock:
            if n > self._reserved:
                raise ValueError(
                    f"unreserve({n}) exceeds outstanding reservation "
                    f"{self._reserved}")
            self._reserved -= n

    def draw(self) -> int:
        """Hand out one physical page against an existing reservation."""
        with self._lock:
            if self._reserved < 1:
                raise RuntimeError(
                    "draw() without a reservation — admission control "
                    "must reserve before the sequence grows")
            self._reserved -= 1
            if not self._free:
                # reserved <= free + reclaimable: the shortfall is
                # covered by unpinned cached chains — evict LRU leaves
                # until a page frees up
                while not self._free:
                    if (self._index is None
                            or not self._index._reclaim_one_locked()):
                        raise RuntimeError(
                            "allocator invariant breached: reservation "
                            "outstanding but no free or reclaimable page")
            page = self._free.popleft()
            self._refcount[page] = 1
            return page

    def free(self, pages: Sequence[int]) -> None:
        """Release references (sequence retired / CoW detach). A page
        returns to the free list when its LAST reference drops."""
        with self._lock:
            self._release_locked(pages)

    def note_eviction(self, n: int = 1) -> None:
        self._m_evicted.inc(n)

    def note_cow(self, n: int = 1) -> None:
        self._m_cow.inc(n)


class _PrefixEntry:
    __slots__ = ("key", "parent", "page", "tokens", "children",
                 "pinned_desc", "last_use")

    def __init__(self, key, parent, page, tokens, last_use):
        self.key = key
        self.parent = parent          # parent entry's key, or None (root)
        self.page = page              # physical page id (index holds 1 ref)
        self.tokens = tokens          # this page's token ids (verification)
        self.children = 0             # resident child entries
        self.pinned_desc = 0          # self-pin + children with pinned_desc>0
        self.last_use = last_use


class PrefixIndex:
    """Hash-consed chain over full-page-aligned token prefixes.

    Each entry caches ONE page keyed by ``blake2s(parent_key ||
    page_tokens)`` — a radix tree flattened to a dict, with the page's
    own tokens stored for collision-proof verification (the parent
    digest binds everything before it). The index holds one allocator
    reference per cached page, so a cached page can never be recycled
    under a reader.

    Pinning: an entry is *self-pinned* while its page has references
    beyond the index's own (a lane mapped it). ``pinned_desc`` counts
    self-pin plus pinned descendants, propagated incrementally on the
    allocator's 1<->2 refcount transitions; an entry with
    ``pinned_desc == 0`` is reclaimable and a reclaimable LEAF may be
    evicted (LRU by ``last_use``) when ``draw()`` runs dry. Eviction is
    therefore exactly refcount-aware: shared pages are refused by
    construction.

    All methods run under the owning allocator's RLock.
    """

    def __init__(self, allocator: PageAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._entries: Dict[bytes, _PrefixEntry] = {}
        self._bypage: Dict[int, bytes] = {}
        self._reclaimable = 0
        self._stamp = 0
        allocator.attach_index(self)

    # -- stats ---------------------------------------------------------

    @property
    def reclaimable(self) -> int:
        return self._reclaimable

    @property
    def cached_pages(self) -> int:
        with self.allocator._lock:
            return len(self._entries)

    # -- key derivation ------------------------------------------------

    def _key(self, parent_key: Optional[bytes], tokens) -> bytes:
        h = hashlib.blake2s(digest_size=16)
        if parent_key is not None:
            h.update(parent_key)
        h.update(np.asarray(tokens, np.int64).tobytes())
        return h.digest()

    # -- allocator callbacks (lock held) -------------------------------

    def _adjust(self, entry: Optional[_PrefixEntry], delta: int) -> None:
        """Propagate a pin/unpin up the ancestor chain: each 0<->positive
        transition of ``pinned_desc`` contributes one unit to the parent
        (walk depth is bounded by pages_per_seq)."""
        while entry is not None:
            was = entry.pinned_desc > 0
            entry.pinned_desc += delta
            now = entry.pinned_desc > 0
            if was == now:
                break
            self._reclaimable += -1 if now else 1
            delta = 1 if now else -1
            entry = (self._entries.get(entry.parent)
                     if entry.parent is not None else None)

    def _on_pin(self, page: int) -> None:
        key = self._bypage.get(page)
        if key is not None:
            self._adjust(self._entries[key], +1)

    def _on_unpin(self, page: int) -> None:
        key = self._bypage.get(page)
        if key is not None:
            self._adjust(self._entries[key], -1)

    # -- lookup / register / reclaim -----------------------------------

    def lookup(self, prompt_ids, max_pages: int) -> List[int]:
        """Longest resident full-page prefix of ``prompt_ids`` → its page
        chain (LRU-stamped). Returns physical page ids WITHOUT retaining
        them — pair with ``allocator.admit(need, pages)`` under the
        allocator lock (the engine's admission path does)."""
        ps = self.page_size
        full = min(len(prompt_ids) // ps, int(max_pages))
        pages: List[int] = []
        with self.allocator._lock:
            self._stamp += 1
            parent: Optional[bytes] = None
            for i in range(full):
                toks = tuple(int(t) for t in prompt_ids[i * ps:(i + 1) * ps])
                key = self._key(parent, toks)
                e = self._entries.get(key)
                if e is None or e.tokens != toks:
                    break
                e.last_use = self._stamp
                pages.append(e.page)
                parent = key
            return pages

    def register(self, prompt_ids, pages: Sequence[int]) -> int:
        """Publish a freshly prefilled lane's full-page prefix chain.
        ``pages`` are the lane's held pages for ``prompt_ids``'s full
        pages, in order. Existing keys are kept (only LRU-stamped): the
        cached page holds identical K/V by construction — K/V content is
        a deterministic function of the token prefix. Returns the number
        of NEW entries."""
        ps = self.page_size
        new = 0
        with self.allocator._lock:
            self._stamp += 1
            parent: Optional[bytes] = None
            for i, page in enumerate(pages):
                toks = tuple(int(t)
                             for t in prompt_ids[i * ps:(i + 1) * ps])
                key = self._key(parent, toks)
                e = self._entries.get(key)
                if e is not None:
                    e.last_use = self._stamp
                    parent = key
                    continue
                # index takes its own reference; the lane's reference
                # makes the page immediately self-pinned
                self.allocator._retain_locked(page)
                e = _PrefixEntry(key, parent, page, toks, self._stamp)
                self._entries[key] = e
                self._bypage[page] = key
                if parent is not None:
                    self._entries[parent].children += 1
                if self.allocator._refcount[page] > 1:
                    # seed self-pin, then propagate to ancestors
                    e.pinned_desc = 1
                    pe = (self._entries.get(parent)
                          if parent is not None else None)
                    self._adjust(pe, +1)
                else:
                    self._reclaimable += 1
                parent = key
                new += 1
            return new

    def _reclaim_one_locked(self) -> bool:
        """Evict the LRU reclaimable LEAF, freeing its page. Called by
        ``draw()`` under the allocator lock when the free list is dry.
        O(entries) scan — entries are bounded by num_pages."""
        best: Optional[_PrefixEntry] = None
        for e in self._entries.values():
            if e.pinned_desc == 0 and e.children == 0:
                if best is None or e.last_use < best.last_use:
                    best = e
        if best is None:
            return False
        self._remove_locked(best)
        return True

    def _remove_locked(self, e: _PrefixEntry) -> None:
        del self._entries[e.key]
        del self._bypage[e.page]
        if e.parent is not None:
            pe = self._entries.get(e.parent)
            if pe is not None:
                pe.children -= 1
        self._reclaimable -= 1
        # drops the index's reference: refcount 1 -> 0 -> free list
        self.allocator._release_locked([e.page])

    def flush(self) -> int:
        """Drop every cached chain (pool reset or model swap — the
        cached K/V no longer matches what a hit would read). Pages still
        referenced by live lanes survive until those lanes retire.
        Returns the number of entries dropped."""
        with self.allocator._lock:
            n = len(self._entries)
            for e in self._entries.values():
                self.allocator._release_locked([e.page])
            self._entries.clear()
            self._bypage.clear()
            self._reclaimable = 0
            return n


class PagedKVArena:
    """Per-attention-layer K/V block pools + the shared allocator.

    ``layer_dims`` maps attention vertex name → ``(heads, head_dim)`` in
    the order the decode walker visits them. ``SENTINEL`` (= num_pages,
    one past the pool) marks page-table holes: gathers fill zeros there,
    scatters drop.

    ``kv_dtype="int8"`` swaps each pool for a ``(q_int8, scales)`` tuple
    — ``q_int8`` is ``[num_pages, page_size, h, d]`` int8, ``scales`` is
    ``[num_pages, h]`` f32 per-(page, head) — quantized on write and
    dequantized in ``ops/paged_attention.paged_gather``. Tuples ride the
    engine's donated-pytree dispatch protocol unchanged.
    """

    def __init__(self, layer_dims: Dict[str, Tuple[int, int]], *,
                 num_pages: int, page_size: int, dtype=jnp.float32,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 with_allocator: bool = True,
                 kv_dtype: Optional[str] = None,
                 prefix_cache: bool = False):
        """``with_allocator=False`` builds a POOLS-ONLY shadow arena —
        the speculative-decoding draft model's K/V lives in one of
        these, indexed by the page tables the TARGET's allocator owns
        (one admission/eviction decision covers both models). A shadow
        arena must never allocate (``allocator`` is None) nor register
        page gauges (they would shadow the owning arena's series on a
        shared registry). ``prefix_cache=True`` attaches a
        :class:`PrefixIndex` to the allocator."""
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if not layer_dims:
            raise ValueError("arena needs at least one attention layer")
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', "
                             f"got {kv_dtype!r}")
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.sentinel = self.num_pages
        self.dtype = dtype
        self.kv_dtype = kv_dtype
        self.layer_names = list(layer_dims)
        self._layer_dims = dict(layer_dims)
        self.k_pools: List = []
        self.v_pools: List = []
        self.reset_pools()
        self.allocator = (PageAllocator(num_pages, registry=registry)
                          if with_allocator else None)
        self.prefix_index = (
            PrefixIndex(self.allocator, self.page_size)
            if (prefix_cache and self.allocator is not None) else None)

    def reset_pools(self) -> None:
        """Fresh zero pools. Used at construction AND after a failed
        dispatch: the engine donates the pools into every step, so an
        error mid-dispatch may have consumed the old buffers — rebuilding
        is the only safe recovery (retiring sequences freed the pages;
        zeros are indistinguishable from a fresh arena). NOTE: callers
        recovering a live engine must also ``prefix_index.flush()`` —
        zeroed pools would serve stale prefix hits."""
        self.k_pools = []
        self.v_pools = []
        for h, d in self._layer_dims.values():
            shape = (self.num_pages, self.page_size, h, d)
            if self.kv_dtype == "int8":
                self.k_pools.append((jnp.zeros(shape, jnp.int8),
                                     jnp.zeros((self.num_pages, h),
                                               jnp.float32)))
                self.v_pools.append((jnp.zeros(shape, jnp.int8),
                                     jnp.zeros((self.num_pages, h),
                                               jnp.float32)))
            else:
                self.k_pools.append(jnp.zeros(shape, self.dtype))
                self.v_pools.append(jnp.zeros(shape, self.dtype))

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` (ceil)."""
        return -(-int(n_tokens) // self.page_size)

    def nbytes(self) -> int:
        total = 0
        for p in self.k_pools + self.v_pools:
            if isinstance(p, tuple):
                total += sum(int(x.nbytes) for x in p)
            else:
                total += int(p.nbytes)
        return total
