"""Paged KV-cache arena: preallocated block pools + a page allocator.

The serving-side memory manager behind continuous-batching decode
(PAPERS: vLLM/SOSP'23). Instead of a monolithic ``[b, max_t, f]`` cache
per sequence — whose worst-case length must be reserved up front and
whose slots idle whenever a sequence is shorter — K/V live in per-layer
``[num_pages, page_size, heads, head_dim]`` block pools shared by every
in-flight sequence. Each sequence owns an ordered page table of physical
page ids; pages are handed out lazily as decode advances and returned to
the free list the moment the sequence retires, so HBM holds exactly the
tokens that exist, not the tokens that might.

Two-level accounting:

- **reservation** (admission control): a sequence reserves its worst-case
  page count when admitted — ``ceil((prompt + max_new_tokens) /
  page_size)`` capped at ``pages_per_seq`` — so a RUNNING sequence can
  never deadlock waiting for a page another running sequence holds.
  Reservations are counts, not physical pages.
- **draw** (lazy allocation): physical pages leave the free list one at a
  time, against the reservation, as the sequence actually grows.

Sliding-window overflow is PAGE EVICTION: once a sequence holds
``pages_per_seq`` pages, its oldest page is recycled as the new tail
(the page table rotates, the view base advances by ``page_size``) —
the decode-arena analog of the dense cache's per-token eviction in
``SelfAttentionLayer._apply_streaming``, accounted in
``kv_pages_evicted_total``.

Thread-safety: the allocator locks itself (submit threads reserve while
the decode loop draws); the pools are owned by the decode engine, which
mutates them only under the scheduler's dispatch lock.
"""

from __future__ import annotations

import threading
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..util import metrics as _metrics

__all__ = ["PageAllocator", "PagedKVArena"]


class PageAllocator:
    """Free-list allocator over ``num_pages`` physical pages with
    reservation accounting (see module docstring)."""

    def __init__(self, num_pages: int,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = int(num_pages)
        self._free = deque(range(self.num_pages))
        self._reserved = 0
        self._lock = threading.Lock()
        reg = registry if registry is not None else _metrics.REGISTRY
        self._m_evicted = reg.counter(
            "kv_pages_evicted_total",
            "KV pages recycled by sliding-window eviction")
        # weakly bound callbacks: on a SHARED registry the newest arena's
        # gauges win (per-server registries are the default, as with the
        # serving gauges), and a retired allocator is collectable — a
        # dead ref raises, which drops the series at exposition
        ref = weakref.ref(self)

        def _sample(attr):
            def fn():
                alloc = ref()
                if alloc is None:
                    raise LookupError("allocator retired")
                return float(getattr(alloc, attr))
            return fn

        reg.gauge(
            "kv_pages_in_use",
            "KV arena pages currently owned by live sequences"
        ).set_function(_sample("pages_in_use"))
        reg.gauge(
            "kv_pages_reserved",
            "KV arena pages reserved by admitted sequences but not yet "
            "drawn").set_function(_sample("reserved"))

    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    @property
    def reserved(self) -> int:
        with self._lock:
            return self._reserved

    def available(self) -> int:
        """Pages an admission could still reserve."""
        with self._lock:
            return len(self._free) - self._reserved

    def reserve(self, n: int) -> bool:
        """Reserve ``n`` pages for a sequence about to be admitted.
        False (and no state change) when the arena cannot guarantee
        them."""
        with self._lock:
            if n > len(self._free) - self._reserved:
                return False
            self._reserved += n
            return True

    def unreserve(self, n: int) -> None:
        """Return ``n`` unused reservations (early retirement: EOS before
        max_new_tokens, or a capped window that never grew that far)."""
        with self._lock:
            if n > self._reserved:
                raise ValueError(
                    f"unreserve({n}) exceeds outstanding reservation "
                    f"{self._reserved}")
            self._reserved -= n

    def draw(self) -> int:
        """Hand out one physical page against an existing reservation."""
        with self._lock:
            if self._reserved < 1:
                raise RuntimeError(
                    "draw() without a reservation — admission control "
                    "must reserve before the sequence grows")
            # the reservation invariant (reserved <= free) makes this pop
            # infallible
            self._reserved -= 1
            return self._free.popleft()

    def free(self, pages: Sequence[int]) -> None:
        """Return physical pages to the free list (sequence retired)."""
        with self._lock:
            for p in pages:
                if not (0 <= p < self.num_pages):
                    raise ValueError(f"free() of unknown page {p}")
                self._free.append(p)

    def note_eviction(self, n: int = 1) -> None:
        self._m_evicted.inc(n)


class PagedKVArena:
    """Per-attention-layer K/V block pools + the shared allocator.

    ``layer_dims`` maps attention vertex name → ``(heads, head_dim)`` in
    the order the decode walker visits them. ``SENTINEL`` (= num_pages,
    one past the pool) marks page-table holes: gathers fill zeros there,
    scatters drop.
    """

    def __init__(self, layer_dims: Dict[str, Tuple[int, int]], *,
                 num_pages: int, page_size: int, dtype=jnp.float32,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 with_allocator: bool = True):
        """``with_allocator=False`` builds a POOLS-ONLY shadow arena —
        the speculative-decoding draft model's K/V lives in one of
        these, indexed by the page tables the TARGET's allocator owns
        (one admission/eviction decision covers both models). A shadow
        arena must never allocate (``allocator`` is None) nor register
        page gauges (they would shadow the owning arena's series on a
        shared registry)."""
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if not layer_dims:
            raise ValueError("arena needs at least one attention layer")
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.sentinel = self.num_pages
        self.dtype = dtype
        self.layer_names = list(layer_dims)
        self._layer_dims = dict(layer_dims)
        self.k_pools: List[jnp.ndarray] = []
        self.v_pools: List[jnp.ndarray] = []
        self.reset_pools()
        self.allocator = (PageAllocator(num_pages, registry=registry)
                          if with_allocator else None)

    def reset_pools(self) -> None:
        """Fresh zero pools. Used at construction AND after a failed
        dispatch: the engine donates the pools into every step, so an
        error mid-dispatch may have consumed the old buffers — rebuilding
        is the only safe recovery (retiring sequences freed the pages;
        zeros are indistinguishable from a fresh arena)."""
        self.k_pools = []
        self.v_pools = []
        for h, d in self._layer_dims.values():
            shape = (self.num_pages, self.page_size, h, d)
            self.k_pools.append(jnp.zeros(shape, self.dtype))
            self.v_pools.append(jnp.zeros(shape, self.dtype))

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` (ceil)."""
        return -(-int(n_tokens) // self.page_size)

    def nbytes(self) -> int:
        return sum(int(p.nbytes) for p in self.k_pools + self.v_pools)
