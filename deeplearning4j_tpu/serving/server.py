"""HTTP inference server with micro-batching + continuous-batched decode.

Endpoints:
  POST /predict   {"inputs": [[...], ...]} → {"outputs": [[...], ...]}
  POST /generate  {"prompt_ids": [...], "max_new_tokens": N,
                   "temperature": T, "eos_id": id, "timeout_s": s}
                  → {"tokens": [...], "finish_reason": "eos|max_tokens|
                     deadline", "ttft_ms": ..., "n_generated": N}
                  (requires ``decode=`` — the continuous-batching
                  scheduler over the paged KV arena, serving/decode.py)
  GET  /healthz   {"ok": true, "live": true, "ready": true,
                   "ready_reasons": [], "model": "...", "served": N,
                   "queue_depth": n, "queue_capacity": n,
                   "breaker": "closed|open|half_open", "draining": bool,
                   "model_digest": "...", "model_generation": n,
                   "decode": {"active": n, "queued": n} when enabled}
  GET  /livez     200 {"live": true} while the process can still answer
                  (the batcher loop is up); the *process-restart* signal
  GET  /readyz    200 {"ready": true} only when the replica should be
                  admitted traffic; 503 + the gating reasons while it is
                  draining, fencing for set_model, warming up, or its
                  breaker is open — the *route-around* signal. /healthz
                  historically conflated the two; it now carries both
  GET  /metrics   Prometheus text exposition of this server's registry
  GET  /debug/flightrecorder
                  the process flight recorder's current event ring as
                  JSON (util/flightrecorder.py — the black box)
  GET  /debug/timeline
                  per-request decode timelines + all traces from this
                  server's tracer (util/timeline.py), nested by
                  parentage; ?trace_id= filters to one trace. Incoming
                  ``traceparent`` headers parent the request spans
                  (Dapper-style propagation) and every response carries
                  a ``traceparent`` back
  GET  /debug/health
                  training-health telemetry (util/health.py): latest
                  rule report, stats snapshot, and NaN layer-of-origin
                  attribution
  POST /profile?seconds=N
                  capture a jax.profiler device trace (XPlane) for N
                  seconds (default 1, max 300) into a fresh run
                  directory; returns {"dir": ...}. One capture at a
                  time — 409 while one is in progress.
  POST /model     swap the served model from a checkpoint zip path
                  {"path": "/path/to/model.zip"} — refused (409) while
                  generative sequences are in flight; fenced to a decode
                  step boundary otherwise

Design: requests land in a queue; a batcher thread coalesces up to
``max_batch`` examples (waiting at most ``batch_timeout_ms`` after the
first) into ONE ``model.output`` call — the serving analog of
AsyncDataSetIterator's prefetch coalescing, and the right shape for a
compiled accelerator backend (per-request dispatch would be latency-bound).
Fixed batch buckets avoid per-size recompilation under jit.

Resilience (rides :mod:`deeplearning4j_tpu.util.resilience`):

- **Load shedding**: the request queue is bounded (``max_queue``
  examples); an overloaded server answers 503 + ``Retry-After``
  immediately instead of stacking unbounded latency.
- **Per-request deadlines**: every request carries a deadline
  (``request_timeout_s``); the batcher never spends a model call on a
  request whose client has already given up (expired entries answer 504).
- **Circuit breaker**: consecutive model failures trip the breaker — new
  predicts answer 503 + ``Retry-After`` for the cool-down instead of
  feeding a broken model; one probe batch then decides recovery.
- **Graceful drain**: ``stop(drain=True)`` stops admitting work, answers
  everything already queued, then shuts down — no request is dropped
  mid-flight on a planned restart.

Observability (rides :mod:`deeplearning4j_tpu.util.metrics` /
:mod:`~deeplearning4j_tpu.util.tracing`):

- ``GET /metrics``: request latency histogram split by phase
  (queue_wait / batch_assembly / model_call), responses by code, shed
  by reason, deadline expiries, batch-size histogram, live gauges for
  queue depth / pending requests / breaker state, and breaker state
  transitions (via the breaker's ``on_transition`` hook).
- With a :class:`~deeplearning4j_tpu.util.tracing.Tracer` attached,
  every predict produces parented spans: ``predict`` → ``queue`` (time
  in the bounded queue) and ``batch`` → ``model`` (the coalesced call),
  and the ``serving.infer`` fault seam records which span a scripted
  fault landed in.

Fault seam: ``"serving.infer"`` around the batched model call.
"""

from __future__ import annotations

import contextlib
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..util import faults as _faults
from ..util import metrics as _metrics
from ..util import tracing as _tracing
from ..util.resilience import (SYSTEM_CLOCK, STATE_VALUES, CircuitBreaker,
                               Clock, Deadline)


class ModelSwapRefused(RuntimeError):
    """set_model refused because generative sequences are in flight —
    retriable after drain (HTTP 409 on the /model endpoint)."""


def drain_counter(registry=None) -> _metrics.Counter:
    """``serving_drain_total{result}`` — graceful drains by outcome.

    ``result="ok"`` when everything admitted was answered within the
    timeout; ``result="timeout"`` for the half-drained state, which also
    emits a ``serving_drain_timeout`` flight-recorder event naming the
    requests still in flight."""
    reg = registry if registry is not None else _metrics.REGISTRY
    return reg.counter(
        "serving_drain_total",
        "Graceful drains by result (ok = fully drained within the "
        "timeout; timeout = half-drained, detailed by the "
        "serving_drain_timeout flight event)", ("result",))


class _Pending:
    __slots__ = ("x", "event", "result", "error", "code", "deadline",
                 "enqueued_at", "span", "queue_span")

    def __init__(self, x: np.ndarray, deadline: Deadline):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.code: int = 500
        self.deadline = deadline
        self.enqueued_at = time.perf_counter()
        self.span = None          # request-root tracing span
        self.queue_span = None    # child span covering queue wait


class InferenceServer:
    """Serve ``model.output`` over HTTP (parity: DL4jServeRouteBuilder)."""

    def __init__(self, model, port: int = 0, *, max_batch: int = 64,
                 batch_timeout_ms: float = 5.0,
                 pad_to_buckets: bool = True,
                 max_queue: int = 256,
                 request_timeout_s: float = 30.0,
                 breaker: Optional[CircuitBreaker] = None,
                 clock: Clock = SYSTEM_CLOCK,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 tracer=None, decode=None,
                 warmup_background: bool = False):
        self._model = model
        self.max_batch = int(max_batch)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self.pad_to_buckets = pad_to_buckets
        self.request_timeout_s = float(request_timeout_s)
        self.clock = clock
        self.tracer = tracer
        # per-server registry by default so two servers in one process
        # (tests, blue/green) don't blur each other's numbers; pass
        # metrics.REGISTRY to aggregate into the process default
        self.registry = registry if registry is not None \
            else _metrics.MetricsRegistry()
        self._init_metrics()
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout_s=5.0, clock=clock,
            name="serving-model")
        self._chain_breaker_hook()
        # readiness state (distinct from liveness): warming / swapping /
        # draining each gate admission without implying the process is
        # unhealthy — see /readyz vs /livez
        self._warming = False
        self._swapping = False
        self._model_generation = 0
        self._model_digest: Optional[str] = None
        # continuous-batched generative decode (serving/decode.py):
        # pass a prebuilt DecodeScheduler, or a dict of engine/scheduler
        # kwargs to build one over THIS model and THIS registry
        self.decode = None
        if decode is not None:
            from .decode import DecodeScheduler, PagedDecodeEngine
            if isinstance(decode, DecodeScheduler):
                self.decode = decode
            else:
                cfg = dict(decode)
                sched_kw = {k: cfg.pop(k) for k in
                            ("max_queue", "default_max_new_tokens",
                             "request_timeout_s", "start_thread")
                            if k in cfg}
                # cross-request prefix caching is on by default for
                # served engines (production traffic repeats system
                # prompts); pass prefix_cache=False to opt out
                cfg.setdefault("prefix_cache", True)
                engine = PagedDecodeEngine(model, registry=self.registry,
                                           **cfg)
                # compile the whole bucket ladder before the loop starts:
                # server START pays it, not the first live requests'
                # SLO deadlines
                if not warmup_background:
                    engine.warmup()
                self.decode = DecodeScheduler(
                    engine, clock=clock, registry=self.registry,
                    tracer=tracer, **sched_kw)
                if warmup_background:
                    # fleet replicas warm AFTER the HTTP server is up so
                    # they can register and report ready=false while the
                    # bucket ladder compiles; the dispatch lock is held
                    # so scheduler ticks (and the set_model fence) queue
                    # behind the warmup instead of racing its dispatches
                    self._warming = True

                    def _warm(sched=self.decode, eng=engine):
                        try:
                            with sched._dispatch_lock:
                                eng.warmup()
                        finally:
                            self._warming = False

                    threading.Thread(target=_warm, daemon=True,
                                     name="serving-warmup").start()
        self._queue: "queue.Queue[_Pending]" = queue.Queue(
            maxsize=int(max_queue))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._draining = False
        # admitted-but-unanswered requests; drain() waits on this, not on
        # queue emptiness (an item leaves the queue before it is answered)
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._m_queue_depth.set_function(lambda: float(self._queue.qsize()))
        self._m_pending.set_function(lambda: float(self._pending))
        self._m_breaker_state.set_function(
            lambda: STATE_VALUES.get(self.breaker.state, -1.0))
        self._batcher = threading.Thread(target=self._batch_loop, daemon=True)
        self._batcher.start()

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200, headers=None):
                body = json.dumps(obj).encode()
                outer._m_responses.inc(code=str(code))
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                headers = dict(headers or {})
                # header in → header out: a caller's trace context is
                # echoed (or replaced by the request's own span) so the
                # client can find its spans in /debug/timeline
                tp = headers.pop("traceparent",
                                 self.headers.get("traceparent"))
                if tp:
                    self.send_header("traceparent", tp)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                path = url.path
                if path == "/healthz":
                    self._json(outer._health())
                elif path == "/livez":
                    live = outer.live
                    self._json({"live": live}, 200 if live else 503)
                elif path == "/readyz":
                    reasons = outer.readiness_reasons()
                    self._json({"ready": not reasons,
                                "reasons": reasons},
                               200 if not reasons else 503)
                elif path == "/metrics":
                    _metrics.write_exposition(self, outer.registry)
                    outer._m_responses.inc(code="200")
                elif path == "/debug/flightrecorder":
                    from ..util import flightrecorder as _flight
                    self._json({"events": _flight.jsonable_events()})
                elif path == "/debug/timeline":
                    from ..util import timeline as _timeline
                    q = parse_qs(url.query)
                    # a prebuilt DecodeScheduler may carry its own
                    # tracer — that is where the request spans live
                    tracer = outer.tracer
                    if tracer is None and outer.decode is not None:
                        tracer = outer.decode.tracer
                    if tracer is None:
                        tracer = _tracing.TRACER
                    tid = q.get("trace_id", [None])[0]
                    payload = {
                        "requests": _timeline.request_timelines(
                            tracer, trace_id=tid),
                        "traces": _timeline.trace_summaries(
                            tracer, trace_id=tid)}
                    # repr-stringify odd attribute values, like the
                    # flight-recorder endpoint — debug inspection must
                    # not 500 on one unserializable attribute
                    self._json(json.loads(
                        json.dumps(payload, default=repr)))
                elif path == "/debug/health":
                    # training-health telemetry: latest rule report +
                    # stats snapshot + NaN attribution (util.health)
                    from ..util import health as _health
                    self._json(json.loads(
                        json.dumps(_health.debug_payload(), default=repr)))
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                url = urlparse(self.path)
                if url.path == "/profile":
                    # no JSON body — parameters ride the query string so
                    # `curl -X POST .../profile?seconds=5` just works
                    from ..util.profiling import profile_request
                    body, code = profile_request(parse_qs(url.query))
                    self._json(body, code)
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(length).decode())
                except Exception as e:
                    self._json({"error": f"bad request: {e}"}, 400)
                    return
                trace_ctx = self.headers.get("traceparent")
                if url.path == "/predict":
                    try:
                        x = np.asarray(payload["inputs"], dtype=np.float32)
                    except Exception as e:
                        self._json({"error": f"bad inputs: {e}"}, 400)
                        return
                    out, err, code, retry_after, tp = outer._predict(
                        x, trace_ctx=trace_ctx)
                    headers = {}
                    if retry_after is not None:
                        headers["Retry-After"] = f"{retry_after:.0f}"
                    if tp is not None:
                        headers["traceparent"] = tp
                    if err is not None:
                        self._json({"error": err}, code, headers)
                    else:
                        self._json({"outputs": out.tolist()}, 200,
                                   headers)
                elif url.path == "/generate":
                    body, code, retry_after, tp = outer._generate(
                        payload, trace_ctx=trace_ctx)
                    headers = {}
                    if retry_after is not None:
                        headers["Retry-After"] = f"{retry_after:.0f}"
                    if tp is not None:
                        headers["traceparent"] = tp
                    self._json(body, code, headers)
                elif url.path == "/model":
                    try:
                        outer.swap_model_from(payload["path"])
                        self._json({"ok": True})
                    except ModelSwapRefused as e:
                        # retriable conflict, not a bad request: drain
                        # the in-flight decodes and POST again
                        self._json({"error": str(e)}, 409,
                                   {"Retry-After": "1"})
                    except Exception as e:
                        self._json({"error": str(e)}, 400)
                else:
                    self._json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._serve_thread.start()

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------

    def _init_metrics(self) -> None:
        reg = self.registry
        self._m_responses = reg.counter(
            "serving_responses_total", "HTTP responses by status code",
            ("code",))
        self._m_shed = reg.counter(
            "serving_shed_total",
            "Predict requests shed with 503 before reaching the model",
            ("reason",))
        self._m_deadline_expired = reg.counter(
            "serving_deadline_expired_total",
            "Queued requests answered 504 after their deadline passed")
        self._m_drain = drain_counter(reg)
        self._m_served = reg.counter(
            "serving_examples_served_total",
            "Examples answered 200 through the batched model call")
        # a fixed powers-of-two ladder (the jit bucket shape), NOT derived
        # from max_batch: servers with different max_batch can then share
        # one registry without a bucket-mismatch error
        self._m_batch_size = reg.histogram(
            "serving_batch_size", "Examples coalesced per model call",
            buckets=[float(1 << i) for i in range(11)])   # 1..1024
        self._m_latency = reg.histogram(
            "serving_request_latency_seconds",
            "Per-phase request latency: time in the bounded queue "
            "(queue_wait), coalescing window (batch_assembly), and the "
            "batched model call (model_call)", ("phase",))

        # HBM pressure next to the serving numbers it explains
        from ..util.profiling import register_device_memory_gauges
        register_device_memory_gauges(reg)
        self._m_queue_depth = reg.gauge(
            "serving_queue_depth", "Requests waiting in the bounded queue")
        self._m_pending = reg.gauge(
            "serving_pending_requests", "Admitted but unanswered requests")
        self._m_breaker_state = reg.gauge(
            "serving_breaker_state",
            "Model circuit breaker state (0=closed, 1=half_open, 2=open)")

    def _chain_breaker_hook(self) -> None:
        """Record breaker transitions into this server's registry, on top
        of any hook the injected breaker already carries."""
        from ..util.resilience import metrics_transition_hook
        record = metrics_transition_hook(self.registry)
        prior = self.breaker.on_transition

        def hook(name: str, old: str, new: str) -> None:
            record(name, old, new)
            if prior is not None:
                prior(name, old, new)

        self.breaker.on_transition = hook

    # back-compat: the pre-metrics bare-int attributes, now read-only
    # views over the registry (the racy ``+= 1`` writers are gone)

    @property
    def served(self) -> int:
        """Examples answered 200 (back-compat for /healthz and tests)."""
        return int(self._m_served.value())

    @property
    def shed(self) -> int:
        """Requests shed for load (queue full / draining) — the pre-metrics
        semantics. Breaker rejections are NOT load shedding; they appear
        only as serving_shed_total{reason="breaker_open"} and
        ``breaker.rejected``."""
        return int(self._m_shed.value(reason="queue_full")
                   + self._m_shed.value(reason="draining"))

    # ------------------------------------------------------------------
    # liveness vs readiness (the /healthz split)
    # ------------------------------------------------------------------

    @property
    def live(self) -> bool:
        """Process-level liveness: the serving loops are up. False means
        restart the replica; it says nothing about routability."""
        return not self._stop.is_set() and self._batcher.is_alive()

    def readiness_reasons(self) -> List[str]:
        """Why this replica should NOT be admitted traffic right now
        (empty = ready). Draining, fencing for ``set_model``, warming the
        decode ladder, and an open breaker all gate admission WITHOUT
        implying the process is unhealthy — a router (or LB) routes
        around a not-ready replica instead of shedding at it."""
        reasons = []
        if self._warming:
            reasons.append("warming")
        if self._draining:
            reasons.append("draining")
        if self._swapping:
            reasons.append("model_swap")
        if self._stop.is_set():
            reasons.append("stopped")
        if self.breaker.state == "open":
            reasons.append("breaker_open")
        return reasons

    @property
    def ready(self) -> bool:
        return not self.readiness_reasons()

    @property
    def model_digest(self) -> str:
        """Content digest of the served params (cached; invalidated on
        ``set_model``). Generation-stamped into the fleet registration so
        a rolling deploy can gate on "replica serves the NEW model"."""
        if self._model_digest is None:
            params = getattr(self._model, "params", None)
            if params is None:
                self._model_digest = type(self._model).__name__
            else:
                from ..util.durable import params_digest
                self._model_digest = params_digest(params)[:16]
        return self._model_digest

    @property
    def model_generation(self) -> int:
        """Monotonic count of completed model swaps on this replica."""
        return self._model_generation

    def _health(self) -> dict:
        reasons = self.readiness_reasons()
        h = {"ok": not self._draining
                   and self.breaker.state != "open",
             "live": self.live,
             "ready": not reasons,
             "ready_reasons": reasons,
             "model": type(self._model).__name__,
             "model_digest": self.model_digest,
             "model_generation": self._model_generation,
             "served": self.served,
             "shed": self.shed,
             "queue_depth": self._queue.qsize(),
             "queue_capacity": self._queue.maxsize,
             "breaker": self.breaker.state,
             "draining": self._draining}
        if self.decode is not None:
            h["decode"] = {"active": self.decode.active_count(),
                           "queued": self.decode.queue_depth()}
            eng = self.decode.engine
            index = eng.arena.prefix_index
            if index is not None:
                hits = self.registry.get("kv_prefix_hits_total")
                hit_pages = self.registry.get("kv_prefix_hit_pages_total")
                alloc = eng.arena.allocator
                h["decode"]["prefix_cache"] = {
                    "hits_full": (hits.value(result="full")
                                  if hits else 0.0),
                    "hits_partial": (hits.value(result="partial")
                                     if hits else 0.0),
                    "misses": (hits.value(result="miss")
                               if hits else 0.0),
                    "hit_pages": (hit_pages.value()
                                  if hit_pages else 0.0),
                    "cached_pages": index.cached_pages,
                    "shared_pages": alloc.shared_pages,
                    "kv_dtype": eng.arena.kv_dtype or "fp",
                }
        return h

    def _generate(self, payload: dict, trace_ctx: Optional[str] = None
                  ) -> Tuple[dict, int, Optional[float], Optional[str]]:
        """POST /generate → (body, http_code, retry_after_s,
        traceparent_out). Blocks the handler thread until the scheduler
        finishes the request (the continuous-batching loop runs it
        concurrently with every other in-flight sequence). The caller's
        ``traceparent`` parents the request's decode spans; the response
        header carries the request root span's context back."""
        from .decode import SchedulerDraining, SchedulerSaturated
        if self.decode is None:
            return ({"error": "generative decode not enabled on this "
                              "server (pass decode=)"}, 400, None, None)
        try:
            prompt = payload["prompt_ids"]
        except KeyError:
            return {"error": "missing prompt_ids"}, 400, None, None
        try:
            # coerce up front: a numeric STRING would pass Deadline's
            # float() inside submit and then blow up in the wait
            # arithmetic below with no HTTP response at all
            timeout_s = (None if payload.get("timeout_s") is None
                         else float(payload["timeout_s"]))
        except (TypeError, ValueError) as e:
            return {"error": f"bad timeout_s: {e}"}, 400, None, None
        try:
            req = self.decode.submit(
                prompt, payload.get("max_new_tokens"),
                temperature=float(payload.get("temperature", 0.0)),
                eos_id=payload.get("eos_id"),
                timeout_s=timeout_s,
                seed=payload.get("seed"),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 1.0)),
                trace_ctx=trace_ctx)
        except SchedulerDraining:
            return {"error": "server is draining"}, 503, 1.0, None
        except SchedulerSaturated as e:
            return ({"error": "server overloaded (decode queue full)"},
                    503, e.retry_after, None)
        except (ValueError, TypeError) as e:
            return {"error": f"bad request: {e}"}, 400, None, None
        tp = (_tracing.inject(req.span) if req.span is not None else None)
        budget = (timeout_s if timeout_s is not None
                  else self.decode.request_timeout_s)
        req.wait(timeout=budget + 5.0)
        if req.finish_reason is None:      # scheduler wedged — honest 504
            return {"error": "generation timeout"}, 504, None, tp
        if req.finish_reason == "error":
            # the request died with the ENGINE (pools rebuilt), not on
            # its own terms: return the preserved partial output and a
            # retryable verdict — the contract a fleet router's
            # idempotent replay depends on
            return ({"error": req.error or "decode failed",
                     "retryable": True,
                     "tokens": [int(t) for t in req.tokens],
                     "n_generated": len(req.tokens)}, 500, None, tp)
        if req.finish_reason == "shutdown":
            return ({"error": "server shutting down",
                     "retryable": True}, 503, None, tp)
        if req.finish_reason == "deadline" and not req.tokens:
            return {"error": "request deadline exceeded"}, 504, None, tp
        body = {"tokens": [int(t) for t in req.tokens],
                "finish_reason": req.finish_reason,
                "n_generated": len(req.tokens)}
        if req.t_first_token is not None:
            body["ttft_ms"] = round(
                1000.0 * (req.t_first_token - req.t_submit), 3)
        if req.span is not None:
            body["trace_id"] = req.span.trace_id
        return body, 200, None, tp

    def _predict(self, x: np.ndarray, trace_ctx: Optional[str] = None
                 ) -> Tuple[Optional[np.ndarray], Optional[str],
                            int, Optional[float], Optional[str]]:
        """Returns (outputs, error, http_code, retry_after_s,
        traceparent_out). ``trace_ctx`` (an incoming traceparent header)
        parents the predict span on the caller's trace."""
        if self._draining or self._stop.is_set():
            self._m_shed.inc(reason="draining")
            return None, "server is draining", 503, 1.0, None
        if not self.breaker.allow():
            self._m_shed.inc(reason="breaker_open")
            retry = max(1.0, self.breaker.retry_after())
            return (None, "model circuit open (failing upstream)", 503,
                    retry, None)
        p = _Pending(x, Deadline(self.request_timeout_s, self.clock))
        tp = None
        if self.tracer is not None:
            p.span = self.tracer.start(
                "predict", parent=_tracing.extract(trace_ctx),
                attributes={"examples": int(x.shape[0])})
            p.queue_span = self.tracer.start("queue", parent=p.span)
            tp = _tracing.inject(p.span)
        with self._pending_lock:
            self._pending += 1
        try:
            self._queue.put_nowait(p)
        except queue.Full:
            # bounded-queue load shedding: an honest 503 now beats an
            # unbounded queue that times every client out later
            with self._pending_lock:
                self._pending -= 1
            self._m_shed.inc(reason="queue_full")
            self._end_spans(p, "shed")
            return (None, "server overloaded (queue full)", 503,
                    max(1.0, self.batch_timeout_s), tp)
        p.event.wait(timeout=self.request_timeout_s + 1.0)
        if p.error is not None:
            return None, p.error, p.code, None, tp
        if p.result is None:
            return None, "inference timeout", 504, None, tp
        return p.result, None, 200, None, tp

    @staticmethod
    def _end_spans(p: _Pending, status: Optional[str] = None) -> None:
        if p.queue_span is not None:
            p.queue_span.end(status)
        if p.span is not None:
            p.span.end(status)

    def _finish(self, p: _Pending) -> None:
        """Answer a pending request (exactly once per admitted request)."""
        if p.span is not None:
            # an answer arriving after the deadline was 504'd to the
            # client — the trace must not claim a clean 200
            late = p.error is None and p.deadline.expired
            p.span.set_attribute("code", p.code if p.error is not None
                                 else 200)
            if late:
                p.span.set_attribute("late", True)
            self._end_spans(p, "error" if p.error is not None
                            else ("late" if late else None))
        p.event.set()
        with self._pending_lock:
            self._pending -= 1

    def _dequeued(self, p: _Pending) -> None:
        """Bookkeeping when the batcher pops a request off the queue."""
        self._m_latency.observe(time.perf_counter() - p.enqueued_at,
                                phase="queue_wait")
        if p.queue_span is not None:
            p.queue_span.end()

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._dequeued(first)
            assembly_t0 = time.perf_counter()
            batch = [first]
            n = first.x.shape[0]
            deadline = assembly_t0 + self.batch_timeout_s
            while n < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    p = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                self._dequeued(p)
                batch.append(p)
                n += p.x.shape[0]
            self._m_latency.observe(time.perf_counter() - assembly_t0,
                                    phase="batch_assembly")
            # expired requests: their client already gave up — answer
            # 504 and spend the model call on the live ones only
            live = []
            for p in batch:
                if p.deadline.expired:
                    p.error = "request deadline exceeded"
                    p.code = 504
                    self._m_deadline_expired.inc()
                    self._finish(p)
                else:
                    live.append(p)
            if live:
                self._run_batch(live)

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, max(self.max_batch, n))

    def _run_batch(self, batch: List[_Pending]) -> None:
        batch_span = None
        model_t0 = None
        if self.tracer is not None:
            batch_span = self.tracer.start(
                "batch", parent=batch[0].span,
                attributes={"requests": len(batch)})
        try:
            x = np.concatenate([p.x for p in batch], axis=0)
            n = x.shape[0]
            if batch_span is not None:
                batch_span.set_attribute("examples", n)
            self._m_batch_size.observe(float(n))
            if self.pad_to_buckets:
                b = self._bucket(n)
                if b > n:  # pad to a power-of-two bucket: one jit cache
                    x = np.concatenate(
                        [x, np.zeros((b - n,) + x.shape[1:], x.dtype)])
            model_t0 = time.perf_counter()
            # span() (not start) so the serving.infer seam sees the model
            # span as this thread's active span
            model_ctx = (self.tracer.span("model", parent=batch_span)
                         if self.tracer is not None
                         else contextlib.nullcontext())
            with self._lock, model_ctx:
                _faults.check("serving.infer", {"batch": n})
                out = np.asarray(self._model.output(x))[:n]
            self._m_latency.observe(time.perf_counter() - model_t0,
                                    phase="model_call")
            ofs = 0
            for p in batch:
                k = p.x.shape[0]
                p.result = out[ofs:ofs + k]
                ofs += k
                self._finish(p)
            self._m_served.inc(n)
            self.breaker.record_success()
            if batch_span is not None:
                batch_span.end()
        except Exception as e:
            # a failing model call still has a latency — the histogram
            # must not go blind during the exact window the breaker trips
            if model_t0 is not None:
                self._m_latency.observe(time.perf_counter() - model_t0,
                                        phase="model_call")
            self.breaker.record_failure()
            if batch_span is not None:
                batch_span.end("error")
            for p in batch:
                p.error = f"{type(e).__name__}: {e}"
                p.code = 500
                self._finish(p)

    # ------------------------------------------------------------------

    def set_model(self, model) -> None:
        """Hot-swap the served model (atomic w.r.t. in-flight batches).

        With generative decode enabled the swap is FENCED to a decode
        step boundary and REFUSED while sequences are in flight: a
        mid-decode swap would mis-read every live K/V page (the cache
        holds the old model's activations). Drain first."""
        if self.decode is not None:
            # readiness gates admission for the whole fence window, so a
            # router stops sending BEFORE the swap instead of bouncing
            # off ModelSwapRefused
            self._swapping = True
            try:
                with self.decode.fence() as in_flight:
                    if in_flight:
                        raise ModelSwapRefused(
                            f"refusing model swap: {in_flight} generative "
                            "sequence(s) in flight — drain() first")
                    self.decode.engine.swap_net(model)
                    with self._lock:
                        self._model = model
            finally:
                self._swapping = False
            self._model_digest = None
            self._model_generation += 1
            return
        with self._lock:
            self._model = model
        self._model_digest = None
        self._model_generation += 1

    def swap_model_from(self, path: str) -> None:
        """Load a checkpoint zip (util.serialization) and serve it."""
        from ..util.serialization import load_model
        self.set_model(load_model(path))

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting new work (predicts AND generates answer 503)
        and wait until everything already accepted has been answered —
        including in-flight generative sequences, which keep decoding
        until they finish or hit their own SLO deadline. True if fully
        drained within ``timeout``.

        Outcome is never silent: every drain counts into
        ``serving_drain_total{result}``, and a timeout additionally
        records a ``serving_drain_timeout`` flight event NAMING the
        requests still in flight — half-drained is an operator page with
        attribution, not a bare False."""
        self._draining = True
        deadline = time.perf_counter() + timeout
        ok = True
        if self.decode is not None:
            ok = self.decode.drain(timeout=timeout)
        drained = False
        while time.perf_counter() < deadline:
            with self._pending_lock:
                if self._pending == 0:
                    drained = True
                    break
            time.sleep(0.005)
        if not drained:
            with self._pending_lock:
                drained = self._pending == 0
        ok = ok and drained
        self._m_drain.inc(result="ok" if ok else "timeout")
        if not ok:
            from ..util import flightrecorder as _flight
            _flight.record("serving_drain_timeout",
                           pending_predicts=self._pending,
                           in_flight=self._in_flight_decodes())
        return ok

    def _in_flight_decodes(self) -> List[dict]:
        """Identify the generative requests still active — lane, progress
        and trace id — so a drain timeout names exactly what it left
        behind (the payload of the ``serving_drain_timeout`` event)."""
        if self.decode is None:
            return []
        out = []
        for seq in list(self.decode._active.values()):
            req = seq.req
            out.append({"lane": seq.lane,
                        "prompt_len": len(req.prompt),
                        "generated": len(req.tokens),
                        "max_new_tokens": req.max_new_tokens,
                        "trace_id": (req.span.trace_id
                                     if req.span is not None else None)})
        return out

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful shutdown: by default drains queued requests first so a
        planned restart drops nothing mid-flight."""
        if drain:
            self.drain(timeout)
        if self.decode is not None:
            self.decode.stop()
        self._stop.set()
        # answer anything still queued (drain=False or drain timeout)
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            p.error = "server shutting down"
            p.code = 503
            self._finish(p)
        self._httpd.shutdown()
        self._batcher.join(timeout=5.0)
