"""HTTP inference server with micro-batching.

Endpoints:
  POST /predict   {"inputs": [[...], ...]} → {"outputs": [[...], ...]}
  GET  /healthz   {"ok": true, "model": "...", "served": N}
  POST /model     swap the served model from a checkpoint zip path
                  {"path": "/path/to/model.zip"}

Design: requests land in a queue; a batcher thread coalesces up to
``max_batch`` examples (waiting at most ``batch_timeout_ms`` after the
first) into ONE ``model.output`` call — the serving analog of
AsyncDataSetIterator's prefetch coalescing, and the right shape for a
compiled accelerator backend (per-request dispatch would be latency-bound).
Fixed batch buckets avoid per-size recompilation under jit.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

import numpy as np


class _Pending:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None


class InferenceServer:
    """Serve ``model.output`` over HTTP (parity: DL4jServeRouteBuilder)."""

    def __init__(self, model, port: int = 0, *, max_batch: int = 64,
                 batch_timeout_ms: float = 5.0,
                 pad_to_buckets: bool = True):
        self._model = model
        self.max_batch = int(max_batch)
        self.batch_timeout_s = float(batch_timeout_ms) / 1000.0
        self.pad_to_buckets = pad_to_buckets
        self.served = 0
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._batcher = threading.Thread(target=self._batch_loop, daemon=True)
        self._batcher.start()

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json({"ok": True,
                                "model": type(outer._model).__name__,
                                "served": outer.served})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(length).decode())
                except Exception as e:
                    self._json({"error": f"bad request: {e}"}, 400)
                    return
                if self.path == "/predict":
                    try:
                        x = np.asarray(payload["inputs"], dtype=np.float32)
                    except Exception as e:
                        self._json({"error": f"bad inputs: {e}"}, 400)
                        return
                    out, err = outer._predict(x)
                    if err is not None:
                        self._json({"error": err}, 500)
                    else:
                        self._json({"outputs": out.tolist()})
                elif self.path == "/model":
                    try:
                        outer.swap_model_from(payload["path"])
                        self._json({"ok": True})
                    except Exception as e:
                        self._json({"error": str(e)}, 400)
                else:
                    self._json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._serve_thread.start()

    # ------------------------------------------------------------------

    def _predict(self, x: np.ndarray):
        p = _Pending(x)
        self._queue.put(p)
        p.event.wait(timeout=60.0)
        if p.error is not None:
            return None, p.error
        if p.result is None:
            return None, "inference timeout"
        return p.result, None

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [first]
            n = first.x.shape[0]
            deadline = time.perf_counter() + self.batch_timeout_s
            while n < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    p = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                batch.append(p)
                n += p.x.shape[0]
            self._run_batch(batch)

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, max(self.max_batch, n))

    def _run_batch(self, batch: List[_Pending]) -> None:
        try:
            x = np.concatenate([p.x for p in batch], axis=0)
            n = x.shape[0]
            if self.pad_to_buckets:
                b = self._bucket(n)
                if b > n:  # pad to a power-of-two bucket: one jit cache
                    x = np.concatenate(
                        [x, np.zeros((b - n,) + x.shape[1:], x.dtype)])
            with self._lock:
                out = np.asarray(self._model.output(x))[:n]
            ofs = 0
            for p in batch:
                k = p.x.shape[0]
                p.result = out[ofs:ofs + k]
                ofs += k
                p.event.set()
            self.served += n
        except Exception as e:
            for p in batch:
                p.error = f"{type(e).__name__}: {e}"
                p.event.set()

    # ------------------------------------------------------------------

    def set_model(self, model) -> None:
        """Hot-swap the served model (atomic w.r.t. in-flight batches)."""
        with self._lock:
            self._model = model

    def swap_model_from(self, path: str) -> None:
        """Load a checkpoint zip (util.serialization) and serve it."""
        from ..util.serialization import load_model
        self.set_model(load_model(path))

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
