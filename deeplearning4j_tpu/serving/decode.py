"""Continuous-batching autoregressive decode over a paged KV arena.

The generative-serving analog of the wave-batched ``/predict`` path
(PAPERS: Orca/OSDI'22 in-flight batching + vLLM/SOSP'23 paged KV):
instead of assembling a batch per request wave and holding every lane
until the LONGEST sequence finishes, a persistent decode loop admits new
sequences and retires finished ones (EOS / max-tokens / SLO deadline)
at EVERY decode step, against a fixed-lane token budget. K/V lives in
the shared :class:`~deeplearning4j_tpu.serving.kv_cache.PagedKVArena`,
so a retiring sequence's pages are reusable by the next admission at the
following step — the chip never idles on finished lanes and HBM never
holds worst-case caches for short sequences.

Two layers:

- :class:`PagedDecodeEngine` — owns the model, the arena, and the
  per-bucket jitted step (``models.transformer.paged_decode_forward``
  through ``util.xla.keyed_jit``). The scheduler packs working lanes
  into power-of-two batch buckets × two chunk lengths (1 for decode,
  ``prefill_chunk`` for prefill) — a FIXED trace set, so admission and
  retirement only ever change array contents and ``jit_retraces_total``
  stays pinned at 1 per bucket (tested), while a lone admission
  prefills at [1, C] cost instead of a full-width padded dispatch.
- :class:`DecodeScheduler` — the continuous-batching policy: bounded
  submit queue with shed-by-reason, page-reservation admission control,
  chunked prefill interleaved with decode, per-sequence deadlines,
  decode-aware ``drain()``, and a ``fence()`` that holds the loop at a
  step boundary (mid-decode model swaps are refused through it).

Three decode-step shapes (ISSUE 11 — the host-tick headroom PERF.md r9
measured is the thing being removed):

- ``block_len=1`` (default): the PR-6 host-ticked step — one dispatch,
  one host round-trip per generated token.
- ``block_len=N``: the FUSED loop — ``models.transformer.
  fused_decode_loop`` runs N decode steps (paged scatter, forward,
  on-device sampling, EOS/max-tokens self-retire mask) inside one
  ``lax.while_loop`` dispatch (early exit once every lane retires);
  the scheduler ticks once per block, so host
  bookkeeping amortizes N× and ``decode_host_syncs_total`` grows by 1
  per block instead of per token. N is bucketed to a power of two
  (``util.xla.pow2_bucket``, cap 64) so the trace ladder gains exactly
  one block-length axis.
- ``draft_net=``: SPECULATIVE decoding on top — a small draft model
  (same ``transformer_lm`` family, pools-only shadow arena indexed by
  the SAME page tables) drafts ``draft_k`` tokens per lane in one fused
  scan, the target verifies all of them in one batched K+1 chunk, and
  accept/reject + bonus selection happen on device (Leviathan et al.);
  a block emits 1..K+1 tokens for two dispatches and ONE host sync.

Greedy output through all three is bit-exact against the oracle (the
per-step math is identical; the verify chunk equals sequential feeding
the same way multi-chunk prefill does) — ``tests/test_fused_decode.py``
pins fused == ticked == oracle and speculative == target-only.

Greedy output through this path is BIT-EXACT against the single-sequence
full-cache oracle (``models.transformer.generate``) for every sequence
that stays within the window (prompt + generated ≤ page_size ×
pages_per_seq): the paged gather reassembles the same dense window the
oracle's streaming cache holds, and both paths share ``sample_token``.
``tests/test_decode.py`` pins it. PAST the window the two legitimately
diverge — the arena evicts a PAGE at a time while the oracle slides
token-by-token, so their attention windows differ by up to
``page_size - 1`` positions (both are valid sliding-window decodes;
size the window to the service's max context where exactness past it
matters).

Observability (same metrics plane as the wave path): shed-by-reason
rides ``serving_shed_total``; ``decode_batch_occupancy``,
``kv_pages_in_use``, ``decode_retired_total{reason}``, TTFT and
time-per-output-token histograms land in the scheduler's registry and
the ``/metrics`` exposition when wired into an ``InferenceServer``.

Fault seam: ``"serving.decode_step"`` before every prefill/decode
dispatch (chaos tests script outages at exact step boundaries).
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import transformer as _transformer
from ..nn.conf.attention import SelfAttentionLayer
from ..nn.conf.layers import EmbeddingSequenceLayer
from ..util import faults as _faults
from ..util import flightrecorder as _flight
from ..util import metrics as _metrics
from ..util import tracing as _tracing
from ..util import xla as _xla
from ..util.resilience import SYSTEM_CLOCK, Clock, Deadline
from .kv_cache import PagedKVArena

__all__ = ["PagedDecodeEngine", "DecodeScheduler", "DecodeRequest",
           "SchedulerSaturated", "SchedulerDraining"]


class SchedulerSaturated(RuntimeError):
    """Submit refused: the bounded request queue is full (shed — the
    generative analog of the wave path's queue-full 503)."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = float(retry_after)


class SchedulerDraining(RuntimeError):
    """Submit refused: the scheduler is draining or stopped."""


class DecodeRequest:
    """Handle for one generative request: the scheduler appends tokens as
    they are produced and signals ``event`` on finish. ``finish_reason``
    ∈ {eos, max_tokens, deadline, error, shutdown}.

    ``ttft_breakdown`` (stamped at the first token, when the scheduler
    has a clock that advances) decomposes the measured TTFT into
    components that sum to it: ``queue_wait`` (submit → lane admission),
    ``prefill`` (this request's own prefill-dispatch wall, compile
    excluded), ``compile`` (fresh-trace compiles its prefill ticks
    paid — 0 after ``warmup()``), and ``dispatch`` (the remainder: the
    shared continuous-batching ticks' other dispatches + host
    bookkeeping between admission and the first token)."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "eos_id",
                 "deadline", "rng", "tokens", "finish_reason", "error",
                 "event", "t_submit", "t_admit", "t_first_token",
                 "t_done", "top_k", "top_p", "span", "queue_span",
                 "ttft_breakdown", "prefix_covered_tokens")

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 temperature: float, eos_id: Optional[int],
                 deadline: Deadline, rng, t_submit: float,
                 top_k: int = 0, top_p: float = 1.0):
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.deadline = deadline
        self.rng = rng
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.event = threading.Event()
        self.t_submit = t_submit
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.span = None            # request-root tracing span
        self.queue_span = None      # child span covering queue wait
        self.ttft_breakdown: Optional[Dict[str, float]] = None
        # prompt tokens covered by a prefix-cache hit at admission
        # (0 = miss or caching disabled) — stamped by the scheduler
        self.prefix_covered_tokens = 0

    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    @property
    def retryable(self) -> bool:
        """True when the request died with the ENGINE (pools rebuilt
        after a dispatch failure, server shutting down) rather than on
        its own terms — safe to replay elsewhere because no terminal
        answer was produced and any partial ``tokens`` are preserved.
        This is the contract a fleet router's idempotent replay rides."""
        return self.finish_reason in ("error", "shutdown")

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes (True) or ``timeout`` real
        seconds pass (False)."""
        return self.event.wait(timeout)


# sequence states inside the scheduler
_PREFILL, _DECODE = "prefill", "decode"


class _Sequence:
    __slots__ = ("req", "lane", "state", "cursor", "last_token",
                 "prefill_s", "compile_s", "covered")

    def __init__(self, req: DecodeRequest, lane: int):
        self.req = req
        self.lane = lane
        self.state = _PREFILL
        self.cursor = 0              # prompt tokens already prefilled
        self.last_token = 0          # next token to feed in decode
        self.prefill_s = 0.0         # own prefill dispatch wall (TTFT)
        self.compile_s = 0.0         # compile wall its ticks paid
        self.covered = 0             # positions below this are cache-hit
        #                              (their K/V is resident: fed tokens
        #                              there re-attend but never write)


class PagedDecodeEngine:
    """Model + arena + the per-bucket jitted paged step function.

    ``max_batch`` is the lane count (the decode token budget per step);
    ``page_size × pages_per_seq`` is each lane's attention window (longer
    sequences slide by page eviction); ``num_pages`` defaults to the
    worst case ``max_batch × pages_per_seq`` (no overcommit) — size it
    smaller to let the scheduler queue admissions on page pressure.
    """

    def __init__(self, net, *, max_batch: int = 8, page_size: int = 16,
                 pages_per_seq: int = 8, num_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 block_len: int = 1, draft_net=None, draft_k: int = 4,
                 prefix_cache: bool = False,
                 kv_dtype: Optional[str] = None):
        import jax.numpy as jnp
        self._validate_net(net)
        self.net = net
        self.lanes = int(max_batch)
        self.page_size = int(page_size)
        self.pages_per_seq = int(pages_per_seq)
        self.window = self.page_size * self.pages_per_seq
        if num_pages is None:
            num_pages = self.lanes * self.pages_per_seq
        if self.pages_per_seq > num_pages:
            raise ValueError(
                f"pages_per_seq={self.pages_per_seq} exceeds the arena "
                f"({num_pages} pages) — one sequence could never run")
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk \
            else min(16, self.window)
        if not (1 <= self.prefill_chunk <= self.window):
            raise ValueError(
                f"prefill_chunk={self.prefill_chunk} must be in "
                f"[1, window={self.window}]")
        # fused-block length: bucketed to a power of two (cap 64) so the
        # trace ladder's block axis is a FIXED set however callers
        # configure it; 1 = the host-ticked step
        self.block_len = _xla.pow2_bucket(int(block_len), cap=64)
        if self.block_len > self.window:
            raise ValueError(
                f"block_len={self.block_len} exceeds the window "
                f"({self.window}) — a block must fit the lane's view")
        self.registry = registry if registry is not None \
            else _metrics.MetricsRegistry()
        self._check_decode_config(net)
        attn = _transformer.attention_vertices(net)
        dims = {}
        for name in attn:
            layer = net.conf.vertices[name].layer
            dims[name] = (layer.n_heads, layer.n_in // layer.n_heads)
        # same dtype rule as the dense streaming cache (_zero_state):
        # at least f32, so bf16 compute policies keep exact K/V
        # (kv_dtype="int8" replaces the pools with quantized
        # (codes, scales) tuples — dtype then only names the fp fallback)
        dtype = jnp.promote_types(net.policy.compute_dtype, jnp.float32)
        self.arena = PagedKVArena(dims, num_pages=int(num_pages),
                                  page_size=self.page_size, dtype=dtype,
                                  registry=self.registry,
                                  kv_dtype=kv_dtype,
                                  prefix_cache=bool(prefix_cache))
        self.vocab = self._embed_vocab(net)
        # speculative decoding: the draft model's K/V lives in a
        # pools-only SHADOW arena indexed by the same page tables (one
        # admission/eviction decision covers both models)
        self.draft_net = draft_net
        self.draft_k = int(draft_k)
        self.draft_arena = None
        if draft_net is not None:
            if int(block_len) != 1:
                raise ValueError(
                    "block_len and draft_net are mutually exclusive — "
                    "speculative blocks are draft_k-sized; configure one "
                    "decode-step shape")
            if not (1 <= self.draft_k <= 16):
                raise ValueError(
                    f"draft_k={self.draft_k} out of range [1, 16]")
            if self.draft_k + 1 > self.window:
                raise ValueError(
                    f"draft_k={self.draft_k}+1 exceeds the window "
                    f"({self.window})")
            self._validate_net(draft_net)
            self._check_decode_config(draft_net)
            if self._embed_vocab(draft_net) != self.vocab:
                raise ValueError(
                    f"draft vocab {self._embed_vocab(draft_net)} != "
                    f"target vocab {self.vocab} — accept/reject compares "
                    "distributions over one vocabulary")
            ddims = {}
            for name in _transformer.attention_vertices(draft_net):
                layer = draft_net.conf.vertices[name].layer
                ddims[name] = (layer.n_heads, layer.n_in // layer.n_heads)
            ddtype = jnp.promote_types(draft_net.policy.compute_dtype,
                                       jnp.float32)
            self.draft_arena = PagedKVArena(
                ddims, num_pages=int(num_pages), page_size=self.page_size,
                dtype=ddtype, with_allocator=False, kv_dtype=kv_dtype)
        # per-lane host state
        s, p = self.lanes, self.pages_per_seq
        self._tables = np.full((s, p), self.arena.sentinel, np.int32)
        self._pos = np.zeros(s, np.int64)       # global fed positions
        self._base = np.zeros(s, np.int64)      # evicted positions
        self._held: List[List[int]] = [[] for _ in range(s)]
        self._reserve_left = np.zeros(s, np.int64)
        self._covered = np.zeros(s, np.int64)   # prefix-hit tokens/lane
        self._free_lanes = deque(range(s))
        self._jit_cache: Dict[str, object] = {}
        # prefix-cache observability (the allocator owns the page-level
        # gauge/histogram; admission-level outcomes live here)
        self._m_prefix_hits = self.registry.counter(
            "kv_prefix_hits_total",
            "Prefix-cache admission outcomes: full (whole prompt "
            "resident), partial (some full-page prefix resident), miss",
            ("result",))
        self._m_prefix_pages = self.registry.counter(
            "kv_prefix_hit_pages_total",
            "KV pages mapped from the prefix cache instead of prefilled")
        # host-round-trip accounting (the satellite the fused loop is
        # measured by): every dispatch that synchronizes the host bumps
        # the sync counter and lands in the "dispatch" component of the
        # tick histogram; the scheduler observes the remainder of its
        # tick as "bookkeeping"
        self._m_syncs = self.registry.counter(
            "decode_host_syncs_total",
            "Decode dispatches whose results the host synchronized on")
        self._m_dispatches = self.registry.counter(
            "decode_dispatches_total",
            "Device dispatches issued by the decode engine", ("kind",))
        self._m_tick = self.registry.histogram(
            "decode_host_tick_seconds",
            "Scheduler tick wall split into dispatch (device compute + "
            "sync) vs host bookkeeping components", ("component",),
            buckets=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1, 0.25, 1.0])
        self._tick_dispatch_wall = 0.0
        self._tick_dispatches = 0
        self._warming = False

    # -- construction-time validation ---------------------------------

    @staticmethod
    def _validate_net(net) -> None:
        if not hasattr(net, "topo_order"):
            raise ValueError(
                "paged decode drives a ComputationGraph (transformer_lm)")
        if net.params is None:
            raise ValueError("net is not initialized — call init() first")
        if (len(net.conf.network_inputs) != 1
                or len(net.conf.network_outputs) != 1):
            raise ValueError("paged decode needs exactly one input and "
                             "one output vertex")
        if not _transformer.attention_vertices(net):
            raise ValueError("no causal SelfAttentionLayer vertices — "
                             "nothing to cache")
        in_name = net.conf.network_inputs[0]
        consumers = [n for n in net.topo_order
                     if in_name in net.conf.vertex_inputs[n]]
        if not any(isinstance(getattr(net.conf.vertices[n], "layer", None),
                              EmbeddingSequenceLayer) for n in consumers):
            raise ValueError(
                "paged decode requires the integer-id input path — build "
                "with transformer_lm(..., input_ids=True)")
        for name in net.topo_order:
            v = net.conf.vertices[name]
            layer = getattr(v, "layer", None)
            if isinstance(layer, SelfAttentionLayer):
                if not layer.causal:
                    raise ValueError(
                        f"vertex {name!r}: non-causal attention cannot "
                        "decode incrementally")
                continue
            if layer is not None and hasattr(layer, "_zero_state"):
                raise ValueError(
                    f"vertex {name!r} ({type(layer).__name__}) carries "
                    "recurrent state — paged decode supports attention-"
                    "only sequence mixing")
            if v.init_state(net.policy):
                raise ValueError(
                    f"vertex {name!r} carries persistent state — "
                    "unsupported in paged decode")

    def _check_decode_config(self, net) -> None:
        """The net's own streaming-cache contract must agree with the
        serving window, or served outputs silently diverge from the
        offline oracle: strict layers forbid the sliding window
        outright, and a dense ``max_cache_t`` different from
        ``page_size × pages_per_seq`` means a different attention
        window."""
        for name in _transformer.attention_vertices(net):
            layer = net.conf.vertices[name].layer
            if getattr(layer, "cache_overflow", "evict") == "strict":
                raise ValueError(
                    f"vertex {name!r} sets cache_overflow='strict' — the "
                    "paged serving window slides; serve an evict-mode "
                    "net, or size page_size×pages_per_seq to the full "
                    "context and cap max_new_tokens instead")
            if (layer.max_cache_t is not None
                    and layer.max_cache_t != self.window):
                raise ValueError(
                    f"vertex {name!r} max_cache_t={layer.max_cache_t} != "
                    f"serving window {self.window} (page_size × "
                    "pages_per_seq) — decode through the arena would "
                    "diverge from the net's own streaming semantics")

    @staticmethod
    def _embed_vocab(net) -> int:
        for name in net.topo_order:
            layer = getattr(net.conf.vertices[name], "layer", None)
            if isinstance(layer, EmbeddingSequenceLayer):
                return int(layer.n_in)
        return 0

    # -- lane lifecycle ------------------------------------------------

    def acquire_lane(self, total_tokens: int,
                     prompt=None) -> Optional[int]:
        """Admission: a free lane + a worst-case page reservation, or
        None when either is unavailable (the request stays queued).

        With the prefix cache enabled and ``prompt`` given, the longest
        resident full-page prefix is mapped (retained) into the lane's
        table — those pages skip prefill entirely — and the reservation
        covers only the UNCOVERED pages. Sequences that will outgrow the
        window still reserve the full ``pages_per_seq``: every shared
        page they map may later detach copy-on-write, which draws a
        private replacement. A fully covered prompt re-feeds its LAST
        token with a dropped write (the K/V is already resident; the
        re-feed only produces the first-token distribution), so the
        feed cursor starts at ``len(prompt) - 1``."""
        if not self._free_lanes:
            return None
        alloc = self.arena.allocator
        index = self.arena.prefix_index
        worst = self.arena.pages_for(total_tokens)
        ps = self.page_size
        covered_pages: List[int] = []
        if index is not None and prompt is not None:
            # lookup + admit under one lock: a page the lookup returned
            # cannot be reclaimed before admit() pins it
            with alloc._lock:
                covered_pages = index.lookup(prompt, self.pages_per_seq)
                if worst > self.pages_per_seq:
                    need = self.pages_per_seq      # CoW detaches may draw
                else:
                    need = worst - len(covered_pages)
                if not alloc.admit(need, covered_pages):
                    return None
        else:
            need = min(self.pages_per_seq, worst)
            if not alloc.reserve(need):
                return None
        lane = self._free_lanes.popleft()
        cov = len(covered_pages)
        covered_tokens = cov * ps
        self._base[lane] = 0
        self._reserve_left[lane] = need
        self._held[lane] = list(covered_pages)
        self._tables[lane, :] = self.arena.sentinel
        if cov:
            self._tables[lane, :cov] = covered_pages
        self._covered[lane] = covered_tokens
        # feed resumes after the covered prefix; a full cover re-feeds
        # the last prompt token (write dropped) for its distribution
        if prompt is not None and covered_tokens >= len(prompt):
            self._pos[lane] = len(prompt) - 1
        else:
            self._pos[lane] = covered_tokens
        if index is not None and prompt is not None:
            if covered_tokens == 0:
                self._m_prefix_hits.inc(result="miss")
            elif covered_tokens >= len(prompt):
                self._m_prefix_hits.inc(result="full")
            else:
                self._m_prefix_hits.inc(result="partial")
            if cov:
                self._m_prefix_pages.inc(cov)
        return lane

    def register_prefix(self, lane: int, prompt_ids) -> int:
        """Publish a freshly prefilled lane's full-page prompt prefix to
        the index (no-op without one, or if the lane's window already
        slid — its leading pages no longer hold the prompt's start).
        Called by the scheduler the moment prefill completes, while the
        lane still holds its pages."""
        index = self.arena.prefix_index
        if index is None or self._base[lane] != 0:
            return 0
        full = min(len(prompt_ids) // self.page_size, self.pages_per_seq)
        if full <= 0:
            return 0
        return index.register(prompt_ids, self._held[lane][:full])

    def release_lane(self, lane: int) -> None:
        """Retirement: the lane's page references released (a page
        returns to the free list at refcount 0 — prefix-cached pages
        stay resident under the index's reference), unused reservation
        returned, the lane reusable by the next admission."""
        self.arena.allocator.free(self._held[lane])
        if self._reserve_left[lane]:
            self.arena.allocator.unreserve(int(self._reserve_left[lane]))
        self._held[lane] = []
        self._reserve_left[lane] = 0
        self._covered[lane] = 0
        self._tables[lane, :] = self.arena.sentinel
        self._pos[lane] = 0
        self._base[lane] = 0
        self._free_lanes.append(lane)

    def ensure_pages(self, lane: int, n_new: int) -> None:
        """Pre-dispatch host bookkeeping: make the lane's view hold slots
        for ``n_new`` tokens at positions ``pos .. pos+n_new-1`` —
        recycling the oldest page (window eviction, ``base`` advances)
        when the view is full, lazily drawing reserved pages as the
        sequence grows."""
        if n_new > self.window:
            raise ValueError(f"chunk of {n_new} exceeds the "
                             f"window ({self.window})")
        pos, base = int(self._pos[lane]), int(self._base[lane])
        ps = self.page_size
        held = self._held[lane]
        alloc = self.arena.allocator
        fresh: List[int] = []      # newly drawn pages (stale content)
        while pos + n_new - 1 - base >= self.window:
            # sliding window at page granularity: the oldest page is
            # recycled as the LAST LIVE table entry. Only the live
            # prefix [0, len(held)) shifts — rotating the full row when
            # the table still has sentinel holes would smear a hole into
            # the middle and drop the chunk's writes. The recycled
            # page's stale slots are either overwritten by this chunk
            # or sit beyond the causal mask until they are.
            oldest = held.pop(0)
            if alloc.refcount(oldest) > 1:
                # COPY-ON-WRITE detach: the oldest page is shared (the
                # prefix index and/or another lane still reads it) —
                # recycling it in place would overwrite their K/V.
                # Sharing is full-page only and tails re-prefill from
                # the page boundary, so no content copy is ever needed:
                # release our reference and draw a private tail instead
                # (admission reserved pages_per_seq for window-sliding
                # sequences precisely so these draws cannot fail).
                alloc.free([oldest])
                replacement = alloc.draw()
                self._reserve_left[lane] -= 1
                fresh.append(replacement)
                alloc.note_cow()
            else:
                replacement = oldest
                fresh.append(oldest)   # its rows are all pre-window now
            held.append(replacement)
            n = len(held)
            self._tables[lane, :n - 1] = self._tables[lane, 1:n]
            self._tables[lane, n - 1] = replacement
            base += ps
            alloc.note_eviction()
        last_idx = (pos + n_new - 1 - base) // ps
        while len(held) <= last_idx:
            page = alloc.draw()
            self._reserve_left[lane] -= 1
            self._tables[lane, len(held)] = page
            held.append(page)
            fresh.append(page)
        self._base[lane] = base
        if fresh:
            self._reset_page_scales(fresh)

    def _reset_page_scales(self, pages: List[int]) -> None:
        """int8 arenas: zero the quantization scales of freshly drawn
        pages. A recycled page's scale is a max over its PREVIOUS
        owner's rows — folding new writes into it would quantize them
        needlessly coarsely, and stale codes × zero scale dequantize to
        exact zeros (fp pools get the same hygiene from the causal
        mask). Host-side eager updates on the small ``[num_pages, h]``
        scale arrays, between dispatches, under the scheduler's tick."""
        idx = np.asarray(pages, np.int32)
        for arena in (self.arena, self.draft_arena):
            if arena is None or arena.kv_dtype != "int8":
                continue
            for pools in (arena.k_pools, arena.v_pools):
                for i, (q, s) in enumerate(pools):
                    pools[i] = (q, s.at[idx].set(0.0))

    def advance(self, lane: int, n: int) -> None:
        """Account ``n`` tokens written by the dispatch that just ran."""
        self._pos[lane] += int(n)

    def rel_pos(self, lane: int) -> int:
        """View-relative position of the lane's next token."""
        return int(self._pos[lane] - self._base[lane])

    # -- the jitted paged step ----------------------------------------

    def run(self, ids: np.ndarray, write_slots: np.ndarray,
            rel_pos: np.ndarray, tables: np.ndarray) -> np.ndarray:
        """One paged forward over a COMPACT lane selection (``ids
        [B, t_new]``, ``tables [B, P]`` — the scheduler packs only the
        lanes that actually have work, bucketed to a power of two, so a
        single admitting sequence does not pay a full-width prefill):
        scatter the new tokens' K/V, gather, attend, return probs
        ``[B, t_new, V]`` on host. Pools are donated and replaced, so
        the arena costs one copy of HBM. Jitted once per
        ``(B, t_new, P)`` bucket under a retrace guard — the bucket set
        is fixed (≤ log₂(lanes)+1 sizes × two chunk lengths), so
        steady-state decode never retraces."""
        b, t_new = ids.shape
        name = f"paged_decode[S{b}xT{t_new}xP{self.pages_per_seq}]"

        def step(params, k_pools, v_pools, ids, tables, wslots, rel):
            return _transformer.paged_decode_forward(
                self.net, params, k_pools, v_pools, ids, tables, wslots,
                rel)

        (probs,) = self._dispatch(name, step, self.arena, self.net.params,
                                  (ids, tables, write_slots, rel_pos),
                                  kind="paged")
        return probs

    def _dispatch(self, name: str, step, arena, params, args: tuple, *,
                  kind: str, sync: bool = True) -> list:
        """The ONE copy of the jitted-dispatch protocol every decode
        program goes through: jit ``step`` under the trace-ladder key
        ``name``, call it with ``(params, arena.k_pools, arena.v_pools,
        *args)`` donating the pools, store the returned pools back on
        ``arena``, and account the dispatch. ``step`` must return
        ``(*outputs, k_pools, v_pools)``. A failed dispatch rebuilds
        EVERY arena before re-raising — the pools were donated and may
        already be consumed; the scheduler retires the in-flight batch
        and keeps serving on the fresh pools. ``sync=True`` transfers
        the outputs to host (one host round-trip, counted); ``sync=
        False`` returns them as device arrays (a later sync waits them
        out)."""
        fn = _xla.keyed_jit(
            self._jit_cache, step, extra=name,
            wrap=lambda f: _xla.retrace_guard(f, name, self.registry),
            donate_argnums=(1, 2))
        t0 = time.perf_counter()
        try:
            *outputs, k_pools, v_pools = fn(
                params, arena.k_pools, arena.v_pools, *args)
            arena.k_pools = list(k_pools)
            arena.v_pools = list(v_pools)
            if sync:
                # the sync lives INSIDE the try: on device backends an
                # async kernel failure surfaces here, not at fn() — the
                # rebuild must cover it or the errored pools just stored
                # above would poison every later dispatch (this sync also
                # surfaces failures from earlier sync=False dispatches)
                outputs = [np.asarray(o) for o in outputs]
        except Exception:
            self._reset_all_pools()
            raise
        self._note_dispatch(t0, kind, sync=sync)
        return outputs

    def _reset_all_pools(self) -> None:
        self.arena.reset_pools()
        if self.draft_arena is not None:
            self.draft_arena.reset_pools()
        if self.arena.prefix_index is not None:
            # the cached chains point into pools that just became zeros —
            # serving a hit from them would read garbage
            self.arena.prefix_index.flush()

    def _compile_wall(self) -> float:
        """Total compile wall this engine's registry has seen — deltas
        around a dispatch attribute fresh-trace compiles (a bucket
        ``warmup()`` missed) to the requests that paid for them."""
        h = self.registry.get("xla_compile_seconds")
        return 0.0 if h is None else h.total_sum()

    def _note_dispatch(self, t0: float, kind: str,
                       sync: bool = True) -> None:
        if self._warming:
            # warmup dispatches are compile calls — folding their
            # multi-second walls into the steady-state tick histogram
            # (or the sync/token ratio) would bury the signal the
            # satellite metric exists to show
            return
        dt = time.perf_counter() - t0
        self._tick_dispatch_wall += dt
        self._tick_dispatches += 1
        self._m_dispatches.inc(kind=kind)
        if sync:
            self._m_syncs.inc()
            self._m_tick.observe(dt, component="dispatch")

    # -- fused multi-token block --------------------------------------

    def run_fused(self, last: np.ndarray, tables: np.ndarray,
                  rel: np.ndarray, active: np.ndarray, budget: np.ndarray,
                  eos: np.ndarray, temps: np.ndarray, top_k: np.ndarray,
                  top_p: np.ndarray, uniforms: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One fused block: ``uniforms.shape[1]`` decode steps in ONE
        dispatch through ``models.transformer.fused_decode_loop`` —
        on-device sampling and EOS/budget self-retire included. One
        host sync per block (the satellite ``decode_host_syncs_total``
        measures). Returns host ``(tokens [B, N], valid [B, N],
        n_emitted [B])``."""
        b, n = uniforms.shape
        name = f"fused_decode[S{b}xN{n}xP{self.pages_per_seq}]"

        def step(params, k_pools, v_pools, last, tables, rel, active,
                 budget, eos, temps, tk, tp, u):
            return _transformer.fused_decode_loop(
                self.net, params, k_pools, v_pools, last, tables, rel,
                active, budget, eos, temps, tk, tp, u)

        toks, valid, n_emitted, _done = self._dispatch(
            name, step, self.arena, self.net.params,
            (last, tables, rel, active, budget, eos, temps, top_k, top_p,
             uniforms), kind="fused")
        return toks, valid, n_emitted

    # -- speculative draft / verify -----------------------------------

    def run_draft_prefill(self, ids: np.ndarray, write_slots: np.ndarray,
                          rel_pos: np.ndarray, tables: np.ndarray) -> None:
        """Shadow prefill: the draft model processes the SAME prompt
        chunk into its own pools (same tables, same slots), so its first
        drafting block sees the full context. Output discarded — no host
        sync; an async failure surfaces at the block's verify sync."""
        b, t = ids.shape
        name = f"draft_prefill[S{b}xT{t}xP{self.pages_per_seq}]"

        def step(params, k_pools, v_pools, ids, tables, wslots, rel):
            return _transformer.paged_decode_forward(
                self.draft_net, params, k_pools, v_pools, ids, tables,
                wslots, rel)

        self._dispatch(name, step, self.draft_arena,
                       self.draft_net.params,
                       (ids, tables, write_slots, rel_pos),
                       kind="draft_prefill", sync=False)

    def run_draft(self, last: np.ndarray, tables: np.ndarray,
                  rel: np.ndarray, active: np.ndarray,
                  write_budget: np.ndarray, temps: np.ndarray,
                  top_k: np.ndarray, top_p: np.ndarray,
                  uniforms: np.ndarray):
        """Draft half of a speculative block: K+1 fused steps of the
        draft net (``uniforms [B, K+1]``). Returns DEVICE arrays
        ``(draft_tokens [B, K], draft_dists [B, K, V])`` — they feed
        straight into :meth:`run_verify` with no host sync between."""
        b, k1 = uniforms.shape
        name = f"spec_draft[S{b}xK{k1 - 1}xP{self.pages_per_seq}]"

        def step(params, k_pools, v_pools, last, tables, rel, active,
                 wbudget, temps, tk, tp, u):
            return _transformer.draft_decode_loop(
                self.draft_net, params, k_pools, v_pools, last, tables,
                rel, active, wbudget, temps, tk, tp, u)

        d_toks, d_dists = self._dispatch(
            name, step, self.draft_arena, self.draft_net.params,
            (last, tables, rel, active, write_budget, temps, top_k,
             top_p, uniforms), kind="draft", sync=False)
        return d_toks, d_dists

    def run_verify(self, last: np.ndarray, tables: np.ndarray,
                   rel: np.ndarray, active: np.ndarray,
                   write_budget: np.ndarray, d_toks, d_dists,
                   temps: np.ndarray, top_k: np.ndarray,
                   top_p: np.ndarray, u_accept: np.ndarray,
                   u_fix: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Verify half: one batched K+1 target chunk + on-device
        accept/reject/bonus (``models.transformer.spec_verify``). The
        block's ONE host sync happens here (it also waits out the draft
        dispatch). Returns host ``(emitted [B, K+1], valid [B, K+1],
        accepts [B])``."""
        b, k = u_accept.shape
        name = f"spec_verify[S{b}xK{k}xP{self.pages_per_seq}]"

        def step(params, k_pools, v_pools, last, tables, rel, active,
                 wbudget, d_toks, d_dists, temps, tk, tp, ua, uf):
            return _transformer.spec_verify(
                self.net, params, k_pools, v_pools, last, tables, rel,
                active, wbudget, d_toks, d_dists, temps, tk, tp, ua, uf)

        emitted, valid, accepts = self._dispatch(
            name, step, self.arena, self.net.params,
            (last, tables, rel, active, write_budget, d_toks, d_dists,
             temps, top_k, top_p, u_accept, u_fix), kind="verify")
        return emitted, valid, accepts

    def warmup(self) -> None:
        """Compile the entire fixed trace set — every power-of-two lane
        bucket × the chunk/block shapes the configured mode actually
        dispatches (prefill chunk always; the t=1 ticked step OR the
        fused block OR the draft-prefill/draft/verify triple) — up
        front, so serving cold-start pays compilation here instead of on
        the first live requests. Warmup dispatches carry all-sentinel
        tables and dropped write slots, so they cannot perturb the
        arena."""
        self._warming = True
        try:
            self._warmup_ladder()
        finally:
            self._warming = False

    def _warmup_ladder(self) -> None:
        b = 1
        while True:
            c = self.prefill_chunk
            sentinel_tables = np.full((b, self.pages_per_seq),
                                      self.arena.sentinel, np.int32)
            self.run(np.zeros((b, c), np.int32),
                     np.full((b, c), -1, np.int32),
                     np.zeros(b, np.int32), sentinel_tables)
            inactive = np.zeros(b, bool)
            zeros_f = np.zeros(b, np.float32)
            zeros_i = np.zeros(b, np.int32)
            if self.arena.prefix_index is not None and c > 1:
                # prefix-cache hit ticks re-feed at t=1 (the scheduler
                # collapses an all-≤1-token prefill tick to the decode
                # shape) — compile it in every mode or the first hit
                # pays a mid-serve trace
                self.run(np.zeros((b, 1), np.int32),
                         np.full((b, 1), -1, np.int32),
                         np.zeros(b, np.int32), sentinel_tables)
                if self.draft_net is not None:
                    self.run_draft_prefill(np.zeros((b, 1), np.int32),
                                           np.full((b, 1), -1, np.int32),
                                           np.zeros(b, np.int32),
                                           sentinel_tables)
            if self.draft_net is not None:
                self.run_draft_prefill(np.zeros((b, c), np.int32),
                                       np.full((b, c), -1, np.int32),
                                       np.zeros(b, np.int32),
                                       sentinel_tables)
                d_toks, d_dists = self.run_draft(
                    zeros_i, sentinel_tables, zeros_i, inactive, zeros_i,
                    zeros_f, zeros_i, np.ones(b, np.float32),
                    np.zeros((b, self.draft_k + 1), np.float32))
                self.run_verify(
                    zeros_i, sentinel_tables, zeros_i, inactive, zeros_i,
                    d_toks, d_dists, zeros_f, zeros_i,
                    np.ones(b, np.float32),
                    np.zeros((b, self.draft_k), np.float32),
                    np.zeros((b, self.draft_k + 1), np.float32))
            elif self.block_len > 1:
                self.run_fused(
                    zeros_i, sentinel_tables, zeros_i, inactive, zeros_i,
                    np.full(b, -1, np.int32), zeros_f, zeros_i,
                    np.ones(b, np.float32),
                    np.zeros((b, self.block_len), np.float32))
            else:
                self.run(np.zeros((b, 1), np.int32),
                         np.full((b, 1), -1, np.int32),
                         np.zeros(b, np.int32), sentinel_tables)
            if b >= self.lanes:
                break
            b <<= 1           # same ladder _compact produces

    # -- model swap (fenced by the scheduler) -------------------------

    def swap_net(self, net) -> None:
        """Replace the served model at a step boundary. The topology must
        match (same vertices, same param shapes) — paged state is laid
        out per attention vertex; a different graph would silently
        mis-read it. Clears the trace cache (the old traces closed over
        the old net object)."""
        self._validate_net(net)
        self._check_decode_config(net)
        if list(net.topo_order) != list(self.net.topo_order):
            raise ValueError("model swap with a different graph topology")
        import jax
        old_shapes = jax.tree_util.tree_map(lambda a: tuple(a.shape),
                                            self.net.params)
        new_shapes = jax.tree_util.tree_map(lambda a: tuple(a.shape),
                                            net.params)
        if old_shapes != new_shapes:
            raise ValueError("model swap with different parameter shapes")
        self.net = net
        self._jit_cache.clear()
        if self.arena.prefix_index is not None:
            # cached K/V was computed by the OLD params — a post-swap
            # prefix hit would silently decode against the wrong model
            self.arena.prefix_index.flush()
        # recompile the trace ladder NOW, while the caller holds the
        # fence — otherwise the first post-swap requests pay per-bucket
        # compilation inside the decode loop with their deadlines burning
        self.warmup()

    def lanes_free(self) -> int:
        return len(self._free_lanes)


class DecodeScheduler:
    """The continuous-batching loop (see module docstring).

    Every tick: retire expired/finished sequences → admit from the
    bounded queue against lanes + page reservations → ONE batched prefill
    chunk for admitting sequences → ONE decode step for every decoding
    sequence. ``step_once()`` is public so deterministic tests drive the
    whole machine on a :class:`ManualClock` with no threads.
    """

    def __init__(self, engine: PagedDecodeEngine, *, max_queue: int = 64,
                 default_max_new_tokens: int = 32,
                 request_timeout_s: float = 30.0,
                 clock: Clock = SYSTEM_CLOCK,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 tracer=None, start_thread: bool = True):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.request_timeout_s = float(request_timeout_s)
        self.clock = clock
        self.tracer = tracer
        self.registry = registry if registry is not None else engine.registry
        self._init_metrics()
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._active: Dict[int, _Sequence] = {}
        # held across one full tick: the step boundary every outside
        # mutation (drain bookkeeping, model swap) must fence on
        self._dispatch_lock = threading.RLock()
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        if start_thread:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def _init_metrics(self) -> None:
        reg = self.registry
        # same family the wave path sheds into — one pane of glass
        self._m_shed = reg.counter(
            "serving_shed_total",
            "Predict requests shed with 503 before reaching the model",
            ("reason",))
        self._m_admitted = reg.counter(
            "decode_admitted_total",
            "Generative sequences admitted into the decode batch")
        self._m_retired = reg.counter(
            "decode_retired_total",
            "Generative sequences retired, by reason", ("reason",))
        self._m_steps = reg.counter(
            "decode_steps_total", "Batched decode steps dispatched")
        self._m_tokens = reg.counter(
            "decode_tokens_total",
            "Tokens pushed through the paged decode path", ("phase",))
        self._m_occupancy = reg.histogram(
            "decode_batch_occupancy",
            "Sequences active in each batched decode step",
            buckets=[float(1 << i) for i in range(11)])
        self._m_ttft = reg.histogram(
            "decode_ttft_seconds",
            "Submit → first generated token (queue + prefill)")
        self._m_tpot = reg.histogram(
            "decode_time_per_output_token_seconds",
            "Steady-state seconds per output token, per finished sequence",
            buckets=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0])
        self._m_draft = reg.counter(
            "decode_draft_tokens_total",
            "Speculative draft tokens, by verify outcome", ("result",))
        # goodput, not just throughput: tokens that were SERVED split by
        # whether their request met its SLO deadline — a saturated
        # scheduler can post high decode_tokens_total while every
        # request deadline-expires half-answered
        self._m_goodput = reg.counter(
            "decode_goodput_tokens_total",
            "Generated tokens by SLO outcome of their request: met "
            "(finished by eos/max_tokens within its deadline) vs missed "
            "(deadline/error/shutdown)", ("slo",))
        # weakly bound, like the arena gauges: a retired scheduler (and
        # through it the engine, params, and pools) must stay
        # collectable even on a shared registry — a dead ref raises,
        # dropping the series at exposition
        ref = weakref.ref(self)

        def _sample(get):
            def fn():
                sched = ref()
                if sched is None:
                    raise LookupError("scheduler retired")
                return float(get(sched))
            return fn

        reg.gauge(
            "decode_active_sequences",
            "Generative sequences currently holding a decode lane"
        ).set_function(_sample(lambda s: len(s._active)))
        reg.gauge(
            "decode_queue_depth",
            "Generative requests accepted but not yet admitted"
        ).set_function(_sample(lambda s: len(s._queue)))

    # -- intake --------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None, *,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None,
               seed: Optional[int] = None, top_k: int = 0,
               top_p: float = 1.0, trace_ctx=None) -> DecodeRequest:
        """Accept one generative request into the bounded queue. Raises
        :class:`SchedulerDraining` / :class:`SchedulerSaturated` (the
        shed paths — recorded by reason) instead of queueing unbounded
        latency. ``top_k``/``top_p`` filter temperature sampling (the
        one semantics shared by the host sampler and the fused device
        loop — see ``ops/sampling.py``); ignored when greedy.

        With a tracer attached, every request gets a root span
        (``decode.request``) with child spans for queue wait, each
        prefill chunk, and each decode/spec block dispatch — the
        per-request timeline ``/debug/timeline`` and
        ``util.timeline.request_timelines`` render. ``trace_ctx`` (a
        traceparent string or extracted SpanContext, e.g. from an HTTP
        header) parents the root span on the caller's trace."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if self.engine.vocab and (prompt.min() < 0
                                  or prompt.max() >= self.engine.vocab):
            raise ValueError(
                f"prompt ids outside [0, {self.engine.vocab})")
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else self.default_max_new_tokens)
        if n_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if int(top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not (0.0 < float(top_p) <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # top_k >= vocab filters nothing — normalize to 0 so the value
        # stays int32-safe in the device block arrays (an unbounded
        # client value would OverflowError inside the tick and
        # error-retire every in-flight sequence)
        top_k = int(top_k)
        if self.engine.vocab and top_k >= self.engine.vocab:
            top_k = 0
        rng = (np.random.default_rng(seed) if temperature > 0 else None)
        req = DecodeRequest(
            prompt, n_new, temperature, eos_id,
            Deadline(timeout_s if timeout_s is not None
                     else self.request_timeout_s, self.clock),
            rng, self.clock.monotonic(), top_k=int(top_k),
            top_p=float(top_p))
        if self.tracer is not None:
            if isinstance(trace_ctx, str):
                trace_ctx = _tracing.extract(trace_ctx)
            req.span = self.tracer.start(
                "decode.request", parent=trace_ctx,
                attributes={"prompt_len": int(prompt.size),
                            "max_new_tokens": n_new})
            req.queue_span = self.tracer.start("queue", parent=req.span)
        try:
            with self._cond:
                # flags checked under the lock: a submit racing stop()
                # must either land before the shutdown flush or be
                # refused — never strand a request in a queue nothing
                # will ever drain
                if self._draining or self._stopped:
                    self._m_shed.inc(reason="draining")
                    _flight.record("decode_shed", reason="draining")
                    raise SchedulerDraining("decode scheduler is draining")
                if len(self._queue) >= self.max_queue:
                    self._m_shed.inc(reason="decode_queue_full")
                    _flight.record("decode_shed",
                                   reason="decode_queue_full",
                                   queue_depth=len(self._queue))
                    raise SchedulerSaturated(
                        "decode queue full", retry_after=1.0)
                self._queue.append(req)
                self._cond.notify_all()
        except Exception:
            self._end_request_spans(req, "shed")
            raise
        return req

    @staticmethod
    def _end_request_spans(req: DecodeRequest,
                           status: Optional[str] = None) -> None:
        if req.queue_span is not None:
            req.queue_span.end(status)
        if req.span is not None:
            req.span.end(status)

    # -- the continuous-batching tick ---------------------------------

    def step_once(self) -> bool:
        """One scheduler tick: retire → admit → prefill chunk → decode
        step. Returns whether anything progressed. Dispatch errors retire
        every in-flight sequence with ``finish_reason="error"`` and leave
        the scheduler serving (the arena's masks make recycled pages
        safe for the next admissions)."""
        with self._dispatch_lock:
            eng = self.engine
            t_tick = time.perf_counter()
            eng._tick_dispatch_wall = 0.0
            eng._tick_dispatches = 0
            progressed = self._retire_expired()
            progressed = self._admit() or progressed
            try:
                progressed = self._prefill_tick() or progressed
                progressed = self._decode_tick() or progressed
            except Exception as e:  # noqa: BLE001 — fail the batch, keep serving
                _flight.record("decode_error",
                               error=f"{type(e).__name__}: {e}",
                               in_flight=len(self._active))
                for seq in list(self._active.values()):
                    seq.req.error = f"{type(e).__name__}: {e}"
                    self._retire(seq, "error")
                progressed = True
            # the measured split behind the fused-block design: dispatch
            # wall (device compute + sync, observed per dispatch by the
            # engine) vs everything else this tick did on the host —
            # only ticks that dispatched count, so idle polling doesn't
            # flood the bookkeeping series
            if eng._tick_dispatches:
                total = time.perf_counter() - t_tick
                eng._m_tick.observe(
                    max(0.0, total - eng._tick_dispatch_wall),
                    component="bookkeeping")
            return progressed

    def _retire_expired(self) -> bool:
        any_ = False
        for seq in list(self._active.values()):
            if seq.req.deadline.expired:
                self._retire(seq, "deadline")
                any_ = True
        with self._cond:
            queued = list(self._queue)
        for req in queued:
            if req.deadline.expired:
                with self._cond:
                    try:
                        self._queue.remove(req)
                    except ValueError:
                        continue
                self._finish(req, "deadline")
                self._m_retired.inc(reason="deadline")
                any_ = True
        return any_

    def _admit(self) -> bool:
        admitted = False
        while True:
            with self._cond:
                if not self._queue:
                    break
                req = self._queue[0]
            lane = self.engine.acquire_lane(
                len(req.prompt) + req.max_new_tokens, prompt=req.prompt)
            if lane is None:          # no lane / page pressure: stay queued
                break
            with self._cond:
                self._queue.popleft()
            req.t_admit = self.clock.monotonic()
            if req.queue_span is not None:
                req.queue_span.set_attribute("lane", lane)
                req.queue_span.end()
                req.queue_span = None
            seq = _Sequence(req, lane)
            # prefix-cache hit: the engine parked the feed cursor past
            # the covered tokens (a full cover re-feeds the last prompt
            # token with its write dropped)
            seq.cursor = int(self.engine._pos[lane])
            seq.covered = int(self.engine._covered[lane])
            req.prefix_covered_tokens = min(seq.covered, len(req.prompt))
            self._active[lane] = seq
            self._m_admitted.inc()
            admitted = True
        return admitted

    def _compact(self, seqs: List[_Sequence], t_new: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                            np.ndarray]:
        """Pack the lanes that actually have work into a power-of-two
        batch bucket: a lone admission prefills at [1, C] cost, not a
        full-width padded dispatch, and the tail of a draining batch
        decodes at [1..] cost — while the bucket SET stays fixed, so the
        retrace pin holds."""
        eng = self.engine
        b = 1
        while b < len(seqs):
            b <<= 1
        ids = np.zeros((b, t_new), np.int32)
        wslots = np.full((b, t_new), -1, np.int32)
        rel = np.zeros(b, np.int32)
        tables = np.full((b, eng.pages_per_seq), eng.arena.sentinel,
                         np.int32)
        for i, seq in enumerate(seqs):
            tables[i] = eng._tables[seq.lane]
        return ids, wslots, rel, tables

    def _prefill_tick(self) -> bool:
        seqs = [s for s in self._active.values() if s.state == _PREFILL]
        if not seqs:
            return False
        eng = self.engine
        c = eng.prefill_chunk
        chunk_len: List[int] = []
        for seq in seqs:
            n = min(c, len(seq.req.prompt) - seq.cursor)
            eng.ensure_pages(seq.lane, n)
            chunk_len.append(n)
        # prefix-cache fast path: when every admitting lane has at most
        # one token left to feed (the full-hit re-feed), dispatch at the
        # t=1 decode shape instead of the padded prefill chunk — hit
        # TTFT collapses to one decode-step cost (warmup compiles [b,1]
        # in every mode when the cache is on, so the retrace pin holds)
        t_feed = (1 if (eng.arena.prefix_index is not None
                        and max(chunk_len) <= 1) else c)
        ids, wslots, rel, tables = self._compact(seqs, t_feed)
        for i, seq in enumerate(seqs):
            n = chunk_len[i]
            r = eng.rel_pos(seq.lane)
            ids[i, :n] = seq.req.prompt[seq.cursor:seq.cursor + n]
            slots = r + np.arange(n)
            if seq.covered > seq.cursor:
                # covered positions are cache-resident: re-fed tokens
                # there attend (their K/V is in the gathered view) but
                # must NOT write — a write would touch a shared page
                slots[:seq.covered - seq.cursor] = -1
            wslots[i, :n] = slots
            rel[i] = r
        _faults.check("serving.decode_step",
                      {"phase": "prefill", "lanes": len(seqs)})
        w0, c0 = eng._tick_dispatch_wall, eng._compile_wall()
        probs = eng.run(ids, wslots, rel, tables)   # [B, C, V]
        if eng.draft_net is not None:
            # shadow prefill: the draft cache must hold the same prompt
            # context before its first drafting block (same ids, same
            # slots, its own pools)
            eng.run_draft_prefill(ids, wslots, rel, tables)
        # TTFT attribution: this chunk's dispatch wall (compile split
        # out) is charged to every sequence it prefilled
        d_wall = eng._tick_dispatch_wall - w0
        d_compile = min(eng._compile_wall() - c0, d_wall)
        for i, seq in enumerate(seqs):
            seq.prefill_s += d_wall - d_compile
            seq.compile_s += d_compile
            if self.tracer is not None and seq.req.span is not None:
                self.tracer.record(
                    "prefill_chunk", d_wall, parent=seq.req.span,
                    attributes={"lane": seq.lane, "bucket": ids.shape[0],
                                "tokens": int(chunk_len[i]),
                                "compile_s": round(d_compile, 6)})
        self._m_tokens.inc(sum(chunk_len), phase="prefill")
        for i, seq in enumerate(seqs):
            n = chunk_len[i]
            eng.advance(seq.lane, n)
            seq.cursor += n
            if seq.cursor == len(seq.req.prompt):
                # publish the prompt's full-page prefix to the cache
                # BEFORE emitting (emit may retire the lane and release
                # its pages); a hit re-registers only as an LRU touch
                eng.register_prefix(seq.lane, seq.req.prompt)
                # the last prompt position's distribution yields the
                # FIRST generated token (TTFT lands here)
                self._emit_token(seq, probs[i, n - 1])
                if seq.lane in self._active:
                    seq.state = _DECODE
        return True

    def _decode_tick(self) -> bool:
        seqs = [s for s in self._active.values() if s.state == _DECODE]
        if not seqs:
            return False
        eng = self.engine
        if eng.draft_net is not None:
            return self._spec_block_tick(seqs)
        if eng.block_len > 1:
            return self._fused_block_tick(seqs)
        for seq in seqs:
            eng.ensure_pages(seq.lane, 1)
        ids, wslots, rel, tables = self._compact(seqs, 1)
        for i, seq in enumerate(seqs):
            r = eng.rel_pos(seq.lane)
            ids[i, 0] = seq.last_token
            wslots[i, 0] = r
            rel[i] = r
        _faults.check("serving.decode_step",
                      {"phase": "decode", "lanes": len(seqs)})
        w0 = eng._tick_dispatch_wall
        probs = eng.run(ids, wslots, rel, tables)   # [B, 1, V]
        self._record_block_spans(seqs, "ticked", ids.shape[0],
                                 [1] * len(seqs),
                                 eng._tick_dispatch_wall - w0)
        self._m_steps.inc()
        self._m_occupancy.observe(float(len(seqs)))
        self._m_tokens.inc(len(seqs), phase="decode")
        # bulk greedy argmax: one vectorized pass instead of a per-lane
        # python round-trip — this loop runs once per generated token
        # across the whole batch (identical result: argmax is invariant
        # under sample_token's monotone float64 cast)
        greedy = np.argmax(probs[:, 0, :], axis=-1)
        for i, seq in enumerate(seqs):
            eng.advance(seq.lane, 1)
            self._emit_token(seq, probs[i, 0],
                             greedy_tok=int(greedy[i]))
        return True

    def _block_arrays(self, seqs: List[_Sequence], n_uniform: int):
        """Per-lane arrays for a fused/speculative block over a
        power-of-two bucket: pending token, view-relative position,
        active mask (padded lanes start retired), per-lane sampling
        config, and ``n_uniform`` host-drawn uniforms per sampled lane
        (from each request's seeded rng — per-request reproducibility is
        independent of batch composition)."""
        eng = self.engine
        b = 1
        while b < len(seqs):
            b <<= 1
        arr = {
            "last": np.zeros(b, np.int32),
            "rel": np.zeros(b, np.int32),
            "active": np.zeros(b, bool),
            "eos": np.full(b, -1, np.int32),
            "temps": np.zeros(b, np.float32),
            "top_k": np.zeros(b, np.int32),
            "top_p": np.ones(b, np.float32),
            "u": np.zeros((b, n_uniform), np.float32),
            "tables": np.full((b, eng.pages_per_seq), eng.arena.sentinel,
                              np.int32),
        }
        for i, seq in enumerate(seqs):
            req = seq.req
            arr["tables"][i] = eng._tables[seq.lane]
            arr["last"][i] = seq.last_token
            arr["rel"][i] = eng.rel_pos(seq.lane)
            arr["active"][i] = True
            if req.eos_id is not None:
                arr["eos"][i] = req.eos_id
            if req.temperature > 0:
                arr["temps"][i] = req.temperature
                arr["top_k"][i] = req.top_k
                arr["top_p"][i] = req.top_p
                arr["u"][i] = req.rng.random(n_uniform)
        return arr

    def _fused_block_tick(self, seqs: List[_Sequence]) -> bool:
        """One FUSED block: N device-resident decode steps, one
        dispatch, one host sync — retire/admit happen at this block
        boundary, finished lanes self-retired on device mid-block."""
        eng = self.engine
        n = eng.block_len
        budgets = []
        for seq in seqs:
            remaining = seq.req.max_new_tokens - len(seq.req.tokens)
            budgets.append(min(n, remaining))
            eng.ensure_pages(seq.lane, budgets[-1])
        a = self._block_arrays(seqs, n)
        budget = np.zeros(a["last"].shape[0], np.int32)
        budget[:len(seqs)] = budgets
        _faults.check("serving.decode_step",
                      {"phase": "decode_block", "lanes": len(seqs),
                       "block_len": n})
        w0 = eng._tick_dispatch_wall
        toks, valid, n_emitted = eng.run_fused(
            a["last"], a["tables"], a["rel"], a["active"], budget,
            a["eos"], a["temps"], a["top_k"], a["top_p"], a["u"])
        self._record_block_spans(
            seqs, "fused", a["last"].shape[0],
            [int(n_emitted[i]) for i in range(len(seqs))],
            eng._tick_dispatch_wall - w0)
        self._m_steps.inc()
        self._m_occupancy.observe(float(len(seqs)))
        emitted_total = 0
        for i, seq in enumerate(seqs):
            m = int(n_emitted[i])
            eng.advance(seq.lane, m)
            emitted_total += m
            for j in range(m):
                self._absorb_token(seq, int(toks[i, j]))
                if seq.req.done:
                    break
        self._m_tokens.inc(emitted_total, phase="decode")
        _flight.record("decode_block", kind="fused", lanes=len(seqs),
                       block_len=n, tokens=emitted_total,
                       active=len(self._active))
        return True

    def _spec_block_tick(self, seqs: List[_Sequence]) -> bool:
        """One SPECULATIVE block: the draft scans K+1 steps, the target
        verifies all K drafts in one batched chunk, accept/reject +
        bonus land on device — 1..K+1 tokens per lane for two dispatches
        and one host sync. EOS/max-tokens truncation of the valid prefix
        is host-side (the block boundary is already a host tick)."""
        eng = self.engine
        k = eng.draft_k
        # write budget = tokens the lane can still emit: slots past it
        # are masked on device, so a lane near max-tokens (or the
        # window edge) never draws pages — or worse, evicts live ones —
        # for positions that cannot exist
        wbudget = []
        for seq in seqs:
            remaining = seq.req.max_new_tokens - len(seq.req.tokens)
            wbudget.append(min(k + 1, remaining))
            eng.ensure_pages(seq.lane, wbudget[-1])
        a = self._block_arrays(seqs, k + 1)
        n_sampled = int(np.count_nonzero(a["temps"] > 0))
        write_budget = np.zeros(a["last"].shape[0], np.int32)
        write_budget[:len(seqs)] = wbudget
        u_acc = np.zeros((a["last"].shape[0], k), np.float32)
        u_fix = np.zeros((a["last"].shape[0], k + 1), np.float32)
        for i, seq in enumerate(seqs):
            if seq.req.temperature > 0:
                u_acc[i] = seq.req.rng.random(k)
                u_fix[i] = seq.req.rng.random(k + 1)
        _faults.check("serving.decode_step",
                      {"phase": "spec_block", "lanes": len(seqs),
                       "draft_k": k})
        w0 = eng._tick_dispatch_wall
        d_toks, d_dists = eng.run_draft(
            a["last"], a["tables"], a["rel"], a["active"], write_budget,
            a["temps"], a["top_k"], a["top_p"], a["u"])
        emitted, valid, accepts = eng.run_verify(
            a["last"], a["tables"], a["rel"], a["active"], write_budget,
            d_toks, d_dists, a["temps"], a["top_k"], a["top_p"], u_acc,
            u_fix)
        spec_wall = eng._tick_dispatch_wall - w0
        self._m_steps.inc()
        self._m_occupancy.observe(float(len(seqs)))
        emitted_total = 0
        emitted_per_seq: List[int] = []
        for i, seq in enumerate(seqs):
            m = 0
            for j in range(k + 1):
                if not valid[i, j]:
                    break
                self._absorb_token(seq, int(emitted[i, j]))
                m += 1
                if seq.req.done:
                    break
            emitted_per_seq.append(m)
            if not seq.req.done:
                # a finished lane was already released by _absorb_token's
                # retire — advancing it would stamp a phantom position
                # onto a freed lane
                eng.advance(seq.lane, m)
            emitted_total += m
            # acceptance accounting over drafts that had a CHANCE of
            # being served (valid context within the write budget):
            # accepted = drafts that became output; rejected = chanced
            # drafts that went unserved — by target mismatch or because
            # the lane finished first (both are wasted draft work, which
            # is what the acceptance rate measures). Beyond-budget
            # drafts are garbage by construction and count as neither.
            chanced = min(k, wbudget[i])
            served = min(int(accepts[i]), m, chanced)
            self._m_draft.inc(served, result="accepted")
            self._m_draft.inc(chanced - served, result="rejected")
        self._record_block_spans(seqs, "speculative",
                                 a["last"].shape[0], emitted_per_seq,
                                 spec_wall)
        self._m_tokens.inc(emitted_total, phase="decode")
        _flight.record("decode_block", kind="speculative",
                       lanes=len(seqs), draft_k=k, tokens=emitted_total,
                       sampled_lanes=n_sampled, active=len(self._active))
        return True

    def _record_block_spans(self, seqs: List[_Sequence], kind: str,
                            bucket: int, tokens: List[int],
                            seconds: float) -> None:
        """Per-request child span for one decode/spec block dispatch —
        the request timeline's token-production record (lane, bucket,
        tokens emitted)."""
        if self.tracer is None:
            return
        for i, seq in enumerate(seqs):
            if seq.req.span is not None:
                self.tracer.record(
                    "decode_block", seconds, parent=seq.req.span,
                    attributes={"kind": kind, "lane": seq.lane,
                                "bucket": int(bucket),
                                "tokens": int(tokens[i])})

    def _emit_token(self, seq: _Sequence, probs: np.ndarray, *,
                    greedy_tok: Optional[int] = None) -> None:
        req = seq.req
        tok = (greedy_tok if greedy_tok is not None
               and req.temperature <= 0.0
               else _transformer.sample_token(probs, req.temperature,
                                              req.rng, top_k=req.top_k,
                                              top_p=req.top_p))
        self._absorb_token(seq, tok)

    def _absorb_token(self, seq: _Sequence, tok: int) -> None:
        """Account one generated token (host-sampled by
        :meth:`_emit_token`, or device-sampled inside a fused/spec
        block): append, stamp TTFT, retire on EOS/max-tokens — the ONE
        copy of the finish rules, so device self-retire decisions and
        host bookkeeping cannot disagree."""
        req = seq.req
        if req.t_first_token is None:
            req.t_first_token = self.clock.monotonic()
            ttft = req.t_first_token - req.t_submit
            self._m_ttft.observe(ttft)
            # the decomposition SUMS to the measured TTFT: queue wait
            # (submit → admission) + this request's own prefill dispatch
            # wall + the compiles its ticks paid + everything else the
            # shared ticks did in between (other lanes' dispatches, host
            # bookkeeping). Components use the same clock as the TTFT
            # histogram, so the identity holds by construction.
            queue_wait = max(0.0, (req.t_admit if req.t_admit is not None
                                   else req.t_submit) - req.t_submit)
            prefill = min(seq.prefill_s, max(0.0, ttft - queue_wait))
            compile_s = min(seq.compile_s,
                            max(0.0, ttft - queue_wait - prefill))
            req.ttft_breakdown = {
                "queue_wait": queue_wait, "prefill": prefill,
                "compile": compile_s,
                "dispatch": max(0.0, ttft - queue_wait - prefill
                                - compile_s)}
            if req.span is not None:
                req.span.set_attribute("ttft_ms", round(ttft * 1000, 3))
                req.span.set_attribute(
                    "ttft_breakdown_ms",
                    {k: round(v * 1000, 3)
                     for k, v in req.ttft_breakdown.items()})
        req.tokens.append(tok)
        seq.last_token = tok
        if req.eos_id is not None and tok == req.eos_id:
            self._retire(seq, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._retire(seq, "max_tokens")

    def _retire(self, seq: _Sequence, reason: str) -> None:
        self.engine.release_lane(seq.lane)
        self._active.pop(seq.lane, None)
        self._finish(seq.req, reason)
        self._m_retired.inc(reason=reason)
        _flight.record("decode_retired", reason=reason, lane=seq.lane,
                       tokens=len(seq.req.tokens),
                       active=len(self._active))

    def _finish(self, req: DecodeRequest, reason: str) -> None:
        req.finish_reason = reason
        req.t_done = self.clock.monotonic()
        if req.t_first_token is not None and len(req.tokens) > 1:
            self._m_tpot.observe(
                (req.t_done - req.t_first_token)
                / (len(req.tokens) - 1))
        if req.tokens:
            self._m_goodput.inc(
                len(req.tokens),
                slo="met" if reason in ("eos", "max_tokens")
                else "missed")
        if req.span is not None:
            req.span.set_attribute("finish_reason", reason)
            req.span.set_attribute("tokens", len(req.tokens))
            self._end_request_spans(
                req, None if reason in ("eos", "max_tokens") else reason)
        req.event.set()

    # -- loop / lifecycle ---------------------------------------------

    def _loop(self) -> None:
        while not self._stopped:
            progressed = self.step_once()
            if progressed:
                continue
            with self._cond:
                if self._stopped:
                    break
                if not self._queue and not self._active:
                    self._cond.wait(timeout=0.05)
                else:
                    # queued work that could not admit yet (page/lane
                    # pressure resolves at the next retirement)
                    self._cond.wait(timeout=0.002)

    def active_count(self) -> int:
        return len(self._active)

    def queue_depth(self) -> int:
        return len(self._queue)

    @contextlib.contextmanager
    def fence(self):
        """Hold the scheduler at a step boundary (no dispatch in flight)
        and yield the number of in-flight sequences — the gate a model
        swap must pass through."""
        with self._dispatch_lock:
            yield len(self._active)

    def drain(self, timeout: float = 30.0) -> bool:
        """Decode-aware drain: stop ACCEPTING, keep SCHEDULING — every
        already-accepted request (queued or in flight) finishes, errors,
        or hits its own deadline before the drain reports clean. True if
        fully drained within ``timeout``. Threadless schedulers (tests)
        are stepped inline."""
        self._draining = True
        end = self.clock.monotonic() + timeout
        while self.clock.monotonic() < end:
            if not self._queue and not self._active:
                return True
            if self._thread is None:
                if not self.step_once():
                    self.clock.sleep(0.001)
            else:
                time.sleep(0.002)
        return not self._queue and not self._active

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the loop; anything still queued or in flight finishes
        with ``finish_reason="shutdown"``. If the loop thread is wedged
        inside a hung dispatch (it holds the dispatch lock for the whole
        tick), the lock acquire below times out too and the stranded
        requests are still answered — engine bookkeeping is skipped in
        that case (the process is going down; waiters must not hang with
        it)."""
        self._draining = True
        self._stopped = True
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        fenced = self._dispatch_lock.acquire(timeout=max(0.1, timeout))
        try:
            for seq in list(self._active.values()):
                if fenced:
                    self.engine.release_lane(seq.lane)
                self._active.pop(seq.lane, None)
                self._finish(seq.req, "shutdown")
                self._m_retired.inc(reason="shutdown")
            with self._cond:
                queued, self._queue = list(self._queue), deque()
            for req in queued:
                self._finish(req, "shutdown")
                self._m_retired.inc(reason="shutdown")
        finally:
            if fenced:
                self._dispatch_lock.release()
