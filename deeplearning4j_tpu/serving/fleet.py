"""Serving fleet tier: replica registration, decode-aware routing, and
idempotent failover replay over the elastic coordination substrate.

One :class:`~.server.InferenceServer` process per engine is not an
availability story — a single SIGTERM loses every in-flight decode and
there is no horizontal scale-out path. This module composes the
substrate the repo already has into that story, adding no new substrate:

- **Registration** (:class:`ReplicaAgent`): each replica publishes a
  heartbeat lease into a shared
  :class:`~deeplearning4j_tpu.parallel.elastic.CoordinationStore` via
  :class:`~deeplearning4j_tpu.parallel.elastic.LeaseMembership` — the
  same liveness layer the elastic trainer uses, in its DYNAMIC mode
  (replicas self-register; the router needs no fleet spec). The doc
  advertises routable capacity (free KV pages and lanes from the page
  allocator, decode queue depth), readiness (the ``/readyz`` split:
  draining / fencing for ``set_model`` / warming report ready=false),
  and a generation-stamped model digest. Liveness is ATTESTED, not
  assumed: the heartbeat publishes only through a decode step boundary
  (a bounded try-acquire of the scheduler's dispatch lock), so a wedged
  decode loop stops heartbeating and its lease expires — a background
  thread that heartbeats unconditionally would mask exactly the hang
  the fleet must route around.
- **Routing** (:class:`FleetRouter`): decode-aware, never round-robin —
  admit to the live+ready replica with the most free KV pages (adjusted
  by the router's own in-flight count × the replica's pages-per-seq, so
  a stale heartbeat cannot stampede one replica) and the shortest
  queue. No routable replica sheds AT THE ROUTER on the existing
  ``serving_shed_total`` plane with ``Retry-After`` — after a short
  grace poll (one lease period) that bridges transient empty views:
  a heartbeat landing a beat late, or the last uncordoned replica
  mid-rolling-deploy.
- **Failover** (the headline): every request gets an idempotency key
  and a router-held retry budget. When a replica dies or wedges
  mid-decode (lease lapses, connection drops, or the replica answers a
  *retryable* verdict — the :attr:`DecodeRequest.retryable` contract),
  the router replays the request on a survivor within the request's own
  SLO deadline. The idempotency table returns each key's single
  response to duplicate submissions, so work is never silently dropped
  and never double-served; ``/debug/audit`` exposes the per-key attempt
  trail the chaos tests verify.
- **Tracing**: the caller's ``traceparent`` parents a ``fleet.request``
  root span; each attempt is a ``fleet.replica_call`` child whose
  context is injected into the proxied request, so the replica's
  ``decode.request`` spans share the trace and the router's
  ``/debug/timeline`` shows the router→replica hop. A replay is an
  explicit ``fleet.failover`` span naming from/to replica and reason.
- **Rolling deploy**: :meth:`FleetRouter.rolling_set_model` walks the
  fleet one replica at a time — cordon (routing excludes it; survivors
  absorb the traffic), wait idle, ``POST /model`` behind the replica's
  own drain/fence (retrying 409s), then gate on readiness + a bumped
  model generation before uncordoning. Zero shed increase during the
  roll is an assertable property, not a hope.

Wire format note: replicas and router speak the plain
:class:`~.server.InferenceServer` HTTP API — the fleet tier is a proxy,
not a protocol.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..parallel.elastic import CoordinationStore, LeaseMembership
from ..util import flightrecorder as _flight
from ..util import metrics as _metrics
from ..util import tracing as _tracing

_FLIGHT_KIND = "fleet_membership"


def _reg(registry) -> _metrics.MetricsRegistry:
    return registry if registry is not None else _metrics.REGISTRY


# ----------------------------------------------------------------------
# metric families (factories so the conventions lint can build them)
# ----------------------------------------------------------------------

def requests_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "fleet_requests_total",
        "Requests terminated at the router by outcome (ok, error, shed, "
        "exhausted = retry budget spent, deduplicated = idempotency-key "
        "duplicate answered from the single in-flight/completed result)",
        ("outcome",))


def failovers_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "fleet_failovers_total",
        "Replays of an accepted request on a surviving replica, by what "
        "invalidated the previous attempt (transport = connection "
        "died/timed out, retryable_error = replica answered the "
        "retryable verdict, replica_shed = replica-level 503)",
        ("reason",))


def heartbeats_counter(registry=None) -> _metrics.Counter:
    return _reg(registry).counter(
        "fleet_heartbeats_total",
        "Replica lease heartbeats by result (published, or "
        "skipped_wedged when the decode step boundary could not be "
        "reached — the lease is then allowed to lapse on purpose)",
        ("result",))


def router_latency_histogram(registry=None) -> _metrics.Histogram:
    return _reg(registry).histogram(
        "fleet_request_latency_seconds",
        "Router-side request latency by phase: route (replica "
        "selection), replica_call (one proxied attempt), total "
        "(admission to terminal answer, replays included)", ("phase",))


def live_replicas_gauge(registry=None) -> _metrics.Gauge:
    return _reg(registry).gauge(
        "fleet_live_replicas", "Replicas with an unexpired lease")


def ready_replicas_gauge(registry=None) -> _metrics.Gauge:
    return _reg(registry).gauge(
        "fleet_ready_replicas",
        "Live replicas currently advertising ready=true")


def shed_counter(registry=None) -> _metrics.Counter:
    # the ROUTER sheds on the same plane the replicas do — one family,
    # one alerting rule, wherever in the tier the 503 happens
    return _reg(registry).counter(
        "serving_shed_total",
        "Predict requests shed with 503 before reaching the model",
        ("reason",))


# ----------------------------------------------------------------------
# replica agent
# ----------------------------------------------------------------------

class ReplicaAgent:
    """Registers one :class:`~.server.InferenceServer` in the fleet and
    keeps its lease fresh.

    The heartbeat doc carries everything the router needs to route
    without calling the replica: address, readiness (+ reasons),
    capacity (free KV pages / lanes, queue depth, active sequences),
    and the generation-stamped model digest. ``stop()`` publishes
    ``status="done"`` so a clean leave is a ``done`` membership
    transition, not an evict.
    """

    def __init__(self, server, store: CoordinationStore, *, replica: str,
                 lease_s: float = 2.0,
                 heartbeat_every_s: Optional[float] = None,
                 probe_timeout_s: Optional[float] = None,
                 addr: Optional[str] = None, registry=None):
        self.server = server
        self.replica = str(replica)
        self.lease_s = float(lease_s)
        self.heartbeat_every_s = (max(0.02, self.lease_s / 4.0)
                                  if heartbeat_every_s is None
                                  else float(heartbeat_every_s))
        self.probe_timeout_s = (min(0.5, self.heartbeat_every_s / 2.0)
                                if probe_timeout_s is None
                                else float(probe_timeout_s))
        self.registry = registry if registry is not None else server.registry
        self.membership = LeaseMembership(
            store, observer=self.replica, lease_s=self.lease_s,
            registry=self.registry, flight_kind=_FLIGHT_KIND)
        self.incarnation = self.membership.next_incarnation(self.replica)
        self.addr = addr or f"127.0.0.1:{server.port}"
        self._m_heartbeats = heartbeats_counter(self.registry)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- doc -----------------------------------------------------------

    def capacity(self) -> Dict[str, Any]:
        srv = self.server
        cap: Dict[str, Any] = {"queue_depth": 0, "active": 0}
        if srv.decode is not None:
            cap["queue_depth"] = srv.decode.queue_depth()
            cap["active"] = srv.decode.active_count()
            eng = srv.decode.engine
            cap["free_lanes"] = eng.lanes_free()
            cap["free_pages"] = eng.arena.allocator.available()
            cap["pages_per_seq"] = eng.pages_per_seq
        return cap

    def _doc(self, status: str = "live") -> Dict[str, Any]:
        srv = self.server
        reasons = srv.readiness_reasons()
        return {"host": self.replica, "incarnation": self.incarnation,
                "status": status, "addr": self.addr,
                "ready": not reasons, "ready_reasons": reasons,
                "model_digest": srv.model_digest,
                "model_generation": srv.model_generation,
                "capacity": self.capacity()}

    # -- heartbeat loop ------------------------------------------------

    def beat(self) -> bool:
        """One heartbeat attempt. Publishes only through a decode step
        boundary: a wedged dispatch holds the lock for the whole hang,
        the probe times out, and the lease lapses — which is the signal
        the router fails over on. During background warmup the lock is
        legitimately held for the whole compile, so the probe is skipped
        and the replica registers (ready=false) while it warms."""
        srv = self.server
        if srv.decode is not None and "warming" not in \
                srv.readiness_reasons():
            lock = srv.decode._dispatch_lock
            if not lock.acquire(timeout=self.probe_timeout_s):
                self._m_heartbeats.inc(result="skipped_wedged")
                return False
            try:
                doc = self._doc()
            finally:
                lock.release()
        else:
            doc = self._doc()
        self.membership.publish(self.replica, doc)
        self._m_heartbeats.inc(result="published")
        return True

    def start(self) -> "ReplicaAgent":
        """Publish the first heartbeat (registration) and start the
        lease-keeping thread."""
        self.beat()

        def _loop():
            while not self._stop.wait(self.heartbeat_every_s):
                try:
                    self.beat()
                except Exception:
                    # a failing heartbeat must never kill the agent
                    # thread: a stale lease is exactly the protocol's
                    # failure signal, so failing to publish IS handled
                    self._m_heartbeats.inc(result="error")
        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name=f"fleet-agent-{self.replica}")
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if deregister:
            self.membership.publish(self.replica, self._doc(status="done"))


# ----------------------------------------------------------------------
# router
# ----------------------------------------------------------------------

class _Entry:
    """One idempotency-key slot: the single response every submission of
    the key receives."""
    __slots__ = ("event", "response")

    def __init__(self):
        self.event = threading.Event()
        self.response: Optional[Tuple[dict, int]] = None


class FleetRouter:
    """HTTP front for N registered replicas: decode-aware routing,
    idempotent failover replay, rolling deploy.

    Endpoints:
      POST /generate  routed + replayed; response gains ``replica``,
                      ``attempts`` and ``idempotency_key``
      POST /model     {"path": ...} → rolling deploy across the fleet
      GET  /healthz   router + per-replica membership summary
      GET  /fleet     full lease view (docs included), cordons, inflight
      GET  /metrics   router registry exposition
      GET  /debug/audit     idempotency-keyed attempt trail
      GET  /debug/timeline  fleet.request timelines (router→replica hops)
    """

    def __init__(self, store: CoordinationStore, *, port: int = 0,
                 lease_s: float = 2.0, retry_budget: int = 2,
                 request_timeout_s: float = 30.0,
                 attempt_timeout_s: float = 10.0,
                 view_refresh_s: float = 0.05,
                 shed_grace_s: Optional[float] = None,
                 observer: str = "router", registry=None, tracer=None):
        self.store = store
        self.retry_budget = int(retry_budget)
        self.request_timeout_s = float(request_timeout_s)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.view_refresh_s = float(view_refresh_s)
        # An empty routable set is usually TRANSIENT — a heartbeat
        # arriving a beat late under scheduler jitter, or the last
        # uncordoned replica mid-rolling-deploy — so the router polls
        # the lease view for up to one lease period (bounded by the
        # request's own deadline) before it sheds. Genuine outages
        # still shed; they just pay one grace period first.
        self.shed_grace_s = float(lease_s if shed_grace_s is None
                                  else shed_grace_s)
        self.registry = registry if registry is not None \
            else _metrics.MetricsRegistry()
        self.tracer = tracer
        self.membership = LeaseMembership(
            store, observer=observer, lease_s=float(lease_s),
            registry=self.registry, flight_kind=_FLIGHT_KIND)
        self._m_requests = requests_counter(self.registry)
        self._m_failovers = failovers_counter(self.registry)
        self._m_latency = router_latency_histogram(self.registry)
        self._m_shed = shed_counter(self.registry)
        self._view_lock = threading.Lock()
        self._last_view: Dict[str, dict] = {}
        self._view_ts = -1e18
        live_replicas_gauge(self.registry).set_function(
            lambda: float(sum(1 for v in self._last_view.values()
                              if v["alive"] and not v["done"])))
        ready_replicas_gauge(self.registry).set_function(
            lambda: float(sum(1 for v in self._last_view.values()
                              if v["alive"] and not v["done"]
                              and (v["doc"] or {}).get("ready"))))
        self._inflight: collections.Counter = collections.Counter()
        self._inflight_lock = threading.Lock()
        self._cordoned: set = set()
        self._results: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        self._results_lock = threading.Lock()
        self._audit: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._max_keys = 4096

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200, headers=None):
                body = json.dumps(obj, default=repr).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                headers = dict(headers or {})
                tp = headers.pop("traceparent",
                                 self.headers.get("traceparent"))
                if tp:
                    self.send_header("traceparent", tp)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                path = url.path
                if path == "/healthz":
                    self._json(outer._health())
                elif path == "/fleet":
                    self._json(outer.fleet_state())
                elif path == "/metrics":
                    _metrics.write_exposition(self, outer.registry)
                elif path == "/debug/audit":
                    self._json({"audit": dict(outer._audit)})
                elif path == "/debug/timeline":
                    from ..util import timeline as _timeline
                    q = parse_qs(url.query)
                    tracer = outer.tracer
                    if tracer is None:
                        tracer = _tracing.TRACER
                    tid = q.get("trace_id", [None])[0]
                    payload = {
                        "requests": _timeline.request_timelines(
                            tracer, root_name="fleet.request",
                            trace_id=tid),
                        "traces": _timeline.trace_summaries(
                            tracer, trace_id=tid)}
                    self._json(json.loads(
                        json.dumps(payload, default=repr)))
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                url = urlparse(self.path)
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(length).decode())
                except Exception as e:
                    self._json({"error": f"bad request: {e}"}, 400)
                    return
                if url.path == "/generate":
                    body, code, headers = outer.route_generate(
                        payload,
                        trace_ctx=self.headers.get("traceparent"),
                        idem_key=self.headers.get("x-idempotency-key"))
                    self._json(body, code, headers)
                elif url.path == "/model":
                    try:
                        results = outer.rolling_set_model(payload["path"])
                        self._json({"ok": True, "replicas": results})
                    except Exception as e:
                        self._json({"error": str(e)}, 500)
                else:
                    self._json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fleet-router")
        self._serve_thread.start()

    # -- membership view -----------------------------------------------

    def view(self, force: bool = False) -> Dict[str, dict]:
        """Lease view, cached for ``view_refresh_s`` so a request burst
        does not multiply store reads."""
        now = time.perf_counter()
        with self._view_lock:
            if force or now - self._view_ts >= self.view_refresh_s:
                self._last_view = self.membership.view()
                self._view_ts = now
            return self._last_view

    def _health(self) -> dict:
        view = self.view()
        reps = {h: {"alive": v["alive"], "done": v["done"],
                    "ready": bool((v["doc"] or {}).get("ready")),
                    "age_s": v["age_s"]}
                for h, v in view.items()}
        return {"ok": True, "role": "router", "replicas": reps,
                "live": sum(1 for v in view.values()
                            if v["alive"] and not v["done"]),
                "ready": sum(1 for r in reps.values()
                             if r["ready"] and r["alive"]),
                "cordoned": sorted(self._cordoned)}

    def fleet_state(self) -> dict:
        view = self.view()
        with self._inflight_lock:
            inflight = dict(self._inflight)
        return {"replicas": view, "cordoned": sorted(self._cordoned),
                "inflight": inflight}

    # -- routing policy ------------------------------------------------

    def _pick(self, exclude=()) -> Tuple[Optional[str], Optional[dict]]:
        """Decode-aware selection: the live+ready replica with the most
        free KV pages — discounted by what this router has already sent
        it but the (possibly stale) heartbeat doesn't reflect — then the
        shortest queue. Deliberately not round-robin: a replica running
        long sequences has less room than its turn would claim."""
        view = self.view()
        best, best_score = None, None
        with self._inflight_lock:
            inflight = dict(self._inflight)
        for h in sorted(view):
            v = view[h]
            if h in exclude or h in self._cordoned:
                continue
            if not v["alive"] or v["done"]:
                continue
            doc = v["doc"] or {}
            if not doc.get("ready") or doc.get("status") != "live":
                continue
            cap = doc.get("capacity") or {}
            mine = inflight.get(h, 0)
            free = cap.get("free_pages")
            adj = (free - cap.get("pages_per_seq", 0) * mine
                   if free is not None else 0)
            queue = cap.get("queue_depth", 0) + cap.get("active", 0) + mine
            score = (adj, -queue)
            if best_score is None or score > best_score:
                best, best_score = h, score
        return best, (view[best]["doc"] if best is not None else None)

    # -- HTTP client ---------------------------------------------------

    @staticmethod
    def _call(addr: str, path: str, payload: Optional[dict], *,
              timeout: float, headers: Optional[dict] = None,
              method: str = "POST"
              ) -> Tuple[Optional[int], dict, dict]:
        """(status, body, headers); status None = transport failure
        (connection refused/reset, socket timeout) — the retryable kind."""
        req = urllib.request.Request(
            f"http://{addr}{path}",
            data=(None if payload is None
                  else json.dumps(payload).encode()),
            headers={"Content-Type": "application/json",
                     **(headers or {})},
            method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return (r.status, json.loads(r.read().decode()),
                        dict(r.headers))
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode())
            except Exception:
                body = {"error": str(e)}
            return e.code, body, dict(e.headers)
        except Exception as e:  # URLError, timeout, reset — transport
            return None, {"error": f"{type(e).__name__}: {e}"}, {}

    def _track(self, replica: str, delta: int) -> None:
        with self._inflight_lock:
            self._inflight[replica] += delta
            if self._inflight[replica] <= 0:
                del self._inflight[replica]

    # -- the headline path ---------------------------------------------

    def route_generate(self, payload: dict,
                       trace_ctx: Optional[str] = None,
                       idem_key: Optional[str] = None
                       ) -> Tuple[dict, int, dict]:
        """Route one /generate: pick → proxy → (on a retryable failure)
        replay on a survivor, all inside the request's SLO deadline and
        the router's retry budget. Exactly one response per idempotency
        key, ever."""
        key = str(payload.get("idempotency_key") or idem_key
                  or uuid.uuid4().hex)
        budget = self.request_timeout_s
        try:
            if payload.get("timeout_s") is not None:
                budget = float(payload["timeout_s"])
        except (TypeError, ValueError):
            return {"error": "bad timeout_s"}, 400, {}
        with self._results_lock:
            entry = self._results.get(key)
            owner = entry is None
            if owner:
                entry = _Entry()
                self._results[key] = entry
                while len(self._results) > self._max_keys:
                    _, old = self._results.popitem(last=False)
                    old.event.set()  # never strand a waiter
        if not owner:
            # duplicate submission: the key's single response, not a
            # second serve
            self._m_requests.inc(outcome="deduplicated")
            entry.event.wait(timeout=budget + 5.0)
            if entry.response is None:
                return ({"error": "duplicate of an in-flight request "
                                  "that did not finish"}, 504, {})
            body, code = entry.response
            return dict(body), code, {"x-idempotent-replay": "true"}
        body, code, headers = self._attempts(payload, key, budget,
                                             trace_ctx)
        entry.response = (body, code)
        entry.event.set()
        return body, code, headers

    def _attempts(self, payload: dict, key: str, budget: float,
                  trace_ctx: Optional[str]) -> Tuple[dict, int, dict]:
        t0 = time.perf_counter()
        deadline = t0 + budget
        prompt = payload.get("prompt_ids") or []
        root = None
        if self.tracer is not None:
            root = self.tracer.start(
                "fleet.request", parent=_tracing.extract(trace_ctx),
                attributes={"idempotency_key": key,
                            "prompt_len": len(prompt)})
        tp_root = (_tracing.inject(root) if root is not None else None)
        trail: List[dict] = []

        def _finish(body, code, headers, outcome, status=None):
            self._m_requests.inc(outcome=outcome)
            self._m_latency.observe(time.perf_counter() - t0,
                                    phase="total")
            if root is not None:
                root.set_attribute("attempts", len(trail))
                root.set_attribute("outcome", outcome)
                root.end(status)
            self._audit_put(key, trail, code)
            if tp_root is not None:
                headers = dict(headers, traceparent=tp_root)
            return body, code, headers

        exclude: set = set()
        attempt = 0
        while True:
            rt0 = time.perf_counter()
            replica, doc = self._pick(exclude)
            if replica is None:
                grace_end = min(deadline,
                                time.perf_counter() + self.shed_grace_s)
                while replica is None \
                        and time.perf_counter() < grace_end:
                    time.sleep(0.025)
                    self.view(force=True)
                    replica, doc = self._pick(exclude)
            self._m_latency.observe(time.perf_counter() - rt0,
                                    phase="route")
            if replica is None:
                # shed at the router, same plane as the replicas
                self._m_shed.inc(reason="no_replica")
                retry_after = max(1.0, self.membership.lease_s)
                _flight.record("fleet_shed", key=key,
                               excluded=sorted(exclude),
                               cordoned=sorted(self._cordoned))
                outcome = "shed" if not trail else "exhausted"
                return _finish(
                    {"error": "no routable replica", "retryable": True,
                     "idempotency_key": key}, 503,
                    {"Retry-After": f"{retry_after:.0f}"},
                    outcome, status="shed")
            attempt += 1
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return _finish(
                    {"error": "SLO deadline exhausted at the router",
                     "idempotency_key": key}, 504, {},
                    "timeout", status="timeout")
            call_span = None
            tp_out = trace_ctx
            if self.tracer is not None:
                call_span = self.tracer.start(
                    "fleet.replica_call", parent=root,
                    attributes={"replica": replica, "attempt": attempt})
                tp_out = _tracing.inject(call_span)
            fwd = dict(payload)
            fwd["timeout_s"] = max(0.05, remaining)
            fwd.pop("idempotency_key", None)
            ct0 = time.perf_counter()
            self._track(replica, +1)
            try:
                code, body, _hdrs = self._call(
                    doc["addr"], "/generate", fwd,
                    timeout=min(self.attempt_timeout_s,
                                max(0.05, remaining)),
                    headers={} if tp_out is None
                    else {"traceparent": tp_out,
                          "x-idempotency-key": key})
            finally:
                self._track(replica, -1)
            self._m_latency.observe(time.perf_counter() - ct0,
                                    phase="replica_call")
            trail.append({"replica": replica, "attempt": attempt,
                          "code": code})
            if code == 200:
                if call_span is not None:
                    call_span.end()
                out = dict(body, replica=replica, attempts=attempt,
                           idempotency_key=key)
                return _finish(out, 200, {}, "ok")
            # classify: is the failed attempt safe to replay?
            retryable = (code is None
                         or (code in (500, 502, 503)
                             and (code != 500
                                  or bool(body.get("retryable")))))
            if not retryable:
                if call_span is not None:
                    call_span.end("error")
                return _finish(dict(body, replica=replica,
                                    idempotency_key=key),
                               code, {}, "error", status="error")
            reason = ("transport" if code is None
                      else "replica_shed" if code == 503
                      else "retryable_error")
            if call_span is not None:
                call_span.set_attribute("failed", reason)
                call_span.end("error")
            exclude.add(replica)
            if attempt > self.retry_budget:
                return _finish(
                    {"error": f"retry budget exhausted after {attempt} "
                              "attempts", "retryable": True,
                     "idempotency_key": key}, 503,
                    {"Retry-After": "1"}, "exhausted", status="error")
            # the failover hop, named in the timeline and the black box
            self._m_failovers.inc(reason=reason)
            nxt, _ = self._pick(exclude)
            if self.tracer is not None:
                fspan = self.tracer.start(
                    "fleet.failover", parent=root,
                    attributes={"from_replica": replica,
                                "to_replica": nxt, "reason": reason})
                fspan.end()
            _flight.record("fleet_failover", key=key,
                           from_replica=replica, to_replica=nxt,
                           reason=reason, attempt=attempt)

    def _audit_put(self, key: str, trail: List[dict], code: int) -> None:
        with self._results_lock:
            self._audit[key] = {"attempts": trail, "code": code}
            while len(self._audit) > self._max_keys:
                self._audit.popitem(last=False)

    # -- rolling deploy ------------------------------------------------

    def rolling_set_model(self, path: str, *,
                          drain_timeout_s: float = 30.0,
                          ready_timeout_s: float = 120.0,
                          poll_s: float = 0.05) -> List[dict]:
        """Swap the served model fleet-wide, one replica at a time, with
        zero shed increase: cordon (routing excludes the replica while
        survivors absorb the load), wait until the router has nothing in
        flight there and the replica's decode is idle, ``POST /model``
        behind its drain/fence (409s retried — the fence refuses while
        sequences are in flight), then gate on readiness + a bumped
        model generation before uncordoning and moving on."""
        view = self.view(force=True)
        targets = [(h, v["doc"]) for h, v in sorted(view.items())
                   if v["alive"] and not v["done"] and v["doc"]]
        results = []
        for h, doc in targets:
            addr = doc["addr"]
            code, health, _ = self._call(addr, "/healthz", None,
                                         timeout=5.0, method="GET")
            gen_before = (health or {}).get("model_generation", 0)
            self._cordoned.add(h)
            t0 = time.perf_counter()
            try:
                # 1. idle: nothing of ours in flight, decode quiet
                deadline = t0 + drain_timeout_s
                while time.perf_counter() < deadline:
                    with self._inflight_lock:
                        mine = self._inflight.get(h, 0)
                    code, health, _ = self._call(addr, "/healthz", None,
                                                 timeout=5.0,
                                                 method="GET")
                    dec = (health or {}).get("decode") or {}
                    if mine == 0 and dec.get("active", 0) == 0 \
                            and dec.get("queued", 0) == 0:
                        break
                    time.sleep(poll_s)
                # 2. swap, retrying the fence's 409 until it admits us
                deadline = time.perf_counter() + ready_timeout_s
                while True:
                    code, body, _ = self._call(addr, "/model",
                                               {"path": path},
                                               timeout=ready_timeout_s)
                    if code == 200:
                        break
                    if code == 409 and time.perf_counter() < deadline:
                        time.sleep(poll_s)
                        continue
                    raise RuntimeError(
                        f"model swap on {h} failed: {code} {body}")
                # 3. readiness gate: serving the NEW model, ready again
                while time.perf_counter() < deadline:
                    code, health, _ = self._call(addr, "/healthz", None,
                                                 timeout=5.0,
                                                 method="GET")
                    if code == 200 and health.get("ready") \
                            and health.get("model_generation",
                                           0) > gen_before:
                        break
                    time.sleep(poll_s)
                else:
                    raise RuntimeError(
                        f"{h} did not become ready on the new model")
            finally:
                self._cordoned.discard(h)
            _flight.record("fleet_rolling_deploy", replica=h,
                           model_digest=health.get("model_digest"),
                           generation=health.get("model_generation"),
                           seconds=time.perf_counter() - t0)
            results.append({"replica": h, "ok": True,
                            "model_digest": health.get("model_digest"),
                            "model_generation":
                                health.get("model_generation")})
        return results

    # -- lifecycle -----------------------------------------------------

    def stop(self) -> None:
        self._httpd.shutdown()
        self._serve_thread.join(timeout=5.0)
