"""StatsStorage: pub-sub persistence for training stats (the UI backbone).

Parity: reference ``deeplearning4j-core/.../api/storage/`` —
``StatsStorage.java`` (sessions/types/workers, persistable records, listener
notifications), ``StatsStorageRouter.java``, ``impl/CollectionStatsStorageRouter``;
impls ``InMemoryStatsStorage`` and the MapDB-backed store (here: JSONL file).
"""

from .remote import RemoteUIStatsStorageRouter
from .stats_storage import (FileStatsStorage, InMemoryStatsStorage,
                            Persistable, StatsStorage, StatsStorageListener,
                            StatsStorageMetricsListener, StatsStorageRouter)

__all__ = ["StatsStorage", "InMemoryStatsStorage", "FileStatsStorage",
           "Persistable", "StatsStorageRouter", "StatsStorageListener",
           "StatsStorageMetricsListener", "RemoteUIStatsStorageRouter"]
