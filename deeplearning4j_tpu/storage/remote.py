"""Remote stats routing: POST training stats to a UI server on another host.

Parity: reference ``deeplearning4j-core/.../api/storage/impl/
RemoteUIStatsStorageRouter.java`` — workers/Spark executors route their
``Persistable`` stats records over HTTP to the central UI's
``RemoteReceiverModule``. Here the receiver is the UI server's
``POST /api/remote`` endpoint (:mod:`deeplearning4j_tpu.ui.server`).

Async by design (like the reference): a daemon thread drains a bounded
queue so a slow/unreachable UI never blocks the training loop. Delivery
rides the resilience substrate (:mod:`deeplearning4j_tpu.util.resilience`)
instead of a fixed-count hammer loop: per-record exponential-backoff
retries under a :class:`RetryPolicy`, behind a :class:`CircuitBreaker` —
consecutive failures trip the breaker OPEN and further records are
dropped immediately (stats are best-effort telemetry) until the cool-down
lets one probe through. Clock and transport are injectable, so the whole
failure story is tested deterministically (no real sleeps or sockets);
fault seam: ``"storage.post"``.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
import warnings
from typing import Callable, Optional

from ..util import faults as _faults
from ..util.resilience import (SYSTEM_CLOCK, CircuitBreaker, Clock,
                               RetryPolicy)
from .stats_storage import Persistable, StatsStorageRouter


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Routes records to ``<url>/api/remote`` via HTTP POST."""

    _SENTINEL = object()

    def __init__(self, url: str, *, queue_size: int = 1000,
                 max_retries: int = 3, timeout: float = 5.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 clock: Clock = SYSTEM_CLOCK,
                 transport: Optional[Callable[[str, bytes, float],
                                              None]] = None):
        self.url = url.rstrip("/") + "/api/remote"
        self.timeout = float(timeout)
        self.clock = clock
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=int(max_retries), initial_backoff=0.2,
            max_backoff=5.0, deadline_s=30.0, clock=clock,
            name="remote-ui")
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=5, reset_timeout_s=30.0, clock=clock,
            name=f"remote-ui[{self.url}]")
        self._transport = transport or self._http_post
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._dropped = 0
        self._posted = 0
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    # -- router interface --

    def put_static_info(self, record: Persistable) -> None:
        self._enqueue("static", record)

    def put_update(self, record: Persistable) -> None:
        self._enqueue("update", record)

    # -- internals --

    def _enqueue(self, kind: str, record: Persistable) -> None:
        if self._closed:
            raise ValueError("router is closed")
        try:
            self._queue.put_nowait((kind, record))
        except queue.Full:
            self._dropped += 1

    def _http_post(self, url: str, body: bytes, timeout: float) -> None:
        """Default transport: one HTTP round-trip; raises on any failure."""
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            if not (200 <= r.status < 300):
                raise IOError(f"remote UI returned status {r.status}")

    def _post(self, kind: str, record: Persistable) -> bool:
        body = json.dumps({"kind": kind,
                           "record": json.loads(record.to_json())}).encode()
        for _attempt in self.retry_policy.attempts():
            # the breaker gates every attempt: tripping OPEN mid-loop
            # stops the remaining retries from hammering a dead UI
            if not self.breaker.allow():
                return False
            try:
                _faults.check("storage.post", {"url": self.url,
                                               "body": body})
                self._transport(self.url, body, self.timeout)
            except Exception:
                self.breaker.record_failure()
                continue
            self.breaker.record_success()
            return True
        self.retry_policy.record_give_up()
        return False

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is self._SENTINEL:
                    return
                kind, record = item
                if self._post(kind, record):
                    self._posted += 1
                else:
                    self._dropped += 1
            finally:
                # task_done AFTER the POST so flush() waits for in-flight
                # records, not just an empty queue.
                self._queue.task_done()

    def flush(self, timeout: float = 10.0) -> None:
        """Block until queued records are posted (or timeout)."""
        import time
        q = self._queue
        deadline = time.time() + timeout
        with q.all_tasks_done:
            while q.unfinished_tasks:
                remaining = deadline - time.time()
                if remaining <= 0 or not q.all_tasks_done.wait(remaining):
                    break

    def close(self, timeout: float = 10.0) -> None:
        self.flush(timeout)
        self._closed = True
        self._queue.put(self._SENTINEL)
        self._thread.join(timeout=timeout)
        if self._dropped:
            warnings.warn(
                f"RemoteUIStatsStorageRouter dropped {self._dropped} records "
                f"(posted {self._posted})")
