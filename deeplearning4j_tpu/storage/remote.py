"""Remote stats routing: POST training stats to a UI server on another host.

Parity: reference ``deeplearning4j-core/.../api/storage/impl/
RemoteUIStatsStorageRouter.java`` — workers/Spark executors route their
``Persistable`` stats records over HTTP to the central UI's
``RemoteReceiverModule``. Here the receiver is the UI server's
``POST /api/remote`` endpoint (:mod:`deeplearning4j_tpu.ui.server`).

Async by design (like the reference): a daemon thread drains a bounded
queue so a slow/unreachable UI never blocks the training loop; after
``max_retries`` consecutive failures records are dropped with a warning
(the reference behaves the same — stats are best-effort telemetry).
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
import warnings
from typing import Optional

from .stats_storage import Persistable, StatsStorageRouter


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Routes records to ``<url>/api/remote`` via HTTP POST."""

    _SENTINEL = object()

    def __init__(self, url: str, *, queue_size: int = 1000,
                 max_retries: int = 3, timeout: float = 5.0):
        self.url = url.rstrip("/") + "/api/remote"
        self.max_retries = int(max_retries)
        self.timeout = float(timeout)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._dropped = 0
        self._posted = 0
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    # -- router interface --

    def put_static_info(self, record: Persistable) -> None:
        self._enqueue("static", record)

    def put_update(self, record: Persistable) -> None:
        self._enqueue("update", record)

    # -- internals --

    def _enqueue(self, kind: str, record: Persistable) -> None:
        if self._closed:
            raise ValueError("router is closed")
        try:
            self._queue.put_nowait((kind, record))
        except queue.Full:
            self._dropped += 1

    def _post(self, kind: str, record: Persistable) -> bool:
        body = json.dumps({"kind": kind,
                           "record": json.loads(record.to_json())}).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        for _ in range(self.max_retries):
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    if 200 <= r.status < 300:
                        return True
            except Exception:
                pass
        return False

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is self._SENTINEL:
                    return
                kind, record = item
                if self._post(kind, record):
                    self._posted += 1
                else:
                    self._dropped += 1
            finally:
                # task_done AFTER the POST so flush() waits for in-flight
                # records, not just an empty queue.
                self._queue.task_done()

    def flush(self, timeout: float = 10.0) -> None:
        """Block until queued records are posted (or timeout)."""
        import time
        q = self._queue
        deadline = time.time() + timeout
        with q.all_tasks_done:
            while q.unfinished_tasks:
                remaining = deadline - time.time()
                if remaining <= 0 or not q.all_tasks_done.wait(remaining):
                    break

    def close(self, timeout: float = 10.0) -> None:
        self.flush(timeout)
        self._closed = True
        self._queue.put(self._SENTINEL)
        self._thread.join(timeout=timeout)
        if self._dropped:
            warnings.warn(
                f"RemoteUIStatsStorageRouter dropped {self._dropped} records "
                f"(posted {self._posted})")
