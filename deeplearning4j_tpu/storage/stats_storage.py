"""Stats storage implementations.

Parity: reference ``api/storage/StatsStorage.java`` — records are keyed by
(session_id, type_id, worker_id, timestamp); static info + updates; listeners
get posted events. ``InMemoryStatsStorage`` ↔ reference in-memory impl;
``FileStatsStorage`` (append-only JSONL) ↔ the MapDB-backed store.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class Persistable:
    """One stats record (parity: ``api/storage/Persistable.java``)."""

    session_id: str
    type_id: str
    worker_id: str
    timestamp: float
    data: Dict[str, Any]

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(s: str) -> "Persistable":
        return Persistable(**json.loads(s))


class StatsStorageListener:
    """Event callbacks (parity: ``StatsStorageListener.java``)."""

    def notify(self, event: str, record: Persistable) -> None:
        pass


class StatsStorageRouter:
    """Write-side contract (parity: ``StatsStorageRouter.java``)."""

    def put_static_info(self, record: Persistable) -> None:
        raise NotImplementedError

    def put_update(self, record: Persistable) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write+subscribe storage (parity: ``StatsStorage.java``)."""

    def __init__(self):
        self._static: Dict[Tuple[str, str, str], Persistable] = {}
        self._updates: Dict[Tuple[str, str, str], List[Persistable]] = {}
        self._listeners: List[StatsStorageListener] = []
        self._lock = threading.Lock()

    # -- write --
    def put_static_info(self, record: Persistable) -> None:
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            self._static[key] = record
            self._persist("static", record)
        self._notify("static", record)

    def put_update(self, record: Persistable) -> None:
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            self._updates.setdefault(key, []).append(record)
            self._persist("update", record)
        self._notify("update", record)

    # -- read --
    def list_session_ids(self) -> List[str]:
        with self._lock:
            out = {k[0] for k in self._static} | {k[0] for k in self._updates}
        return sorted(out)

    def list_type_ids(self, session_id: str) -> List[str]:
        with self._lock:
            out = {k[1] for k in list(self._static) + list(self._updates)
                   if k[0] == session_id}
        return sorted(out)

    def list_workers(self, session_id: str, type_id: str) -> List[str]:
        with self._lock:
            out = {k[2] for k in list(self._static) + list(self._updates)
                   if k[0] == session_id and k[1] == type_id}
        return sorted(out)

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[Persistable]:
        # under the lock: writer threads mutate _static concurrently
        with self._lock:
            return self._static.get((session_id, type_id, worker_id))

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str, timestamp: float
                              ) -> List[Persistable]:
        with self._lock:
            recs = self._updates.get((session_id, type_id, worker_id), [])
            return [r for r in recs if r.timestamp > timestamp]

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[Persistable]:
        with self._lock:
            recs = self._updates.get((session_id, type_id, worker_id), [])
            return recs[-1] if recs else None

    # -- subscribe --
    def register_listener(self, listener: StatsStorageListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def _notify(self, event: str, record: Persistable) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for l in listeners:
            l.notify(event, record)

    # -- persistence hook (overridden by FileStatsStorage) --
    def _persist(self, kind: str, record: Persistable) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    pass


class FileStatsStorage(StatsStorage):
    """Append-only JSONL persistence, reloaded on open (parity: the
    reference's MapDB-backed ``FileStatsStorage``). Usable as a context
    manager so the append handle cannot leak::

        with FileStatsStorage(path) as st:
            st.put_update(...)
    """

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    entry = json.loads(line)
                    rec = Persistable(**entry["record"])
                    key = (rec.session_id, rec.type_id, rec.worker_id)
                    if entry["kind"] == "static":
                        self._static[key] = rec
                    else:
                        self._updates.setdefault(key, []).append(rec)
        self._f = open(path, "a")

    def _persist(self, kind: str, record: Persistable) -> None:
        if self._f.closed:
            raise ValueError(f"FileStatsStorage({self.path!r}) is closed")
        self._f.write(json.dumps(
            {"kind": kind, "record": dataclasses.asdict(record)}) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "FileStatsStorage":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class StatsStorageMetricsListener(StatsStorageListener):
    """Counts records routed through a storage, per event kind and
    type_id — ``stats_records_total{event,type_id}`` answers "is the
    remote run still posting?" from one scrape instead of a UI visit."""

    def __init__(self, registry=None):
        from ..util import metrics as _metrics
        reg = registry if registry is not None else _metrics.REGISTRY
        self.records = reg.counter(
            "stats_records_total", "Stats records routed into storage",
            ("event", "type_id"))

    def notify(self, event: str, record: Persistable) -> None:
        self.records.inc(event=event, type_id=record.type_id)
